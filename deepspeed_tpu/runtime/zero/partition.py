"""ZeRO as a GSPMD sharding plan.

The TPU-native re-design of the reference ZeRO implementations:
  * stage 1/2 (``DeepSpeedZeroOptimizer`` runtime/zero/stage_1_and_2.py:125 —
    flattened partitions, IPG bucketing, allgather of updated partitions)
  * stage 3 (``DeepSpeedZeroOptimizer_Stage3`` runtime/zero/stage3.py:129 +
    ``partition_parameters.py`` + ``partitioned_param_coordinator.py`` —
    gather-on-demand hooks, trace-based prefetch)

On TPU none of that machinery is hand-built: ZeRO *is a sharding assignment*.

  stage 0: params/grads/opt-state replicated over ``data`` (grads psum'd)
  stage 1: optimizer state (fp32 master + moments) sharded over ``data``
  stage 2: + gradients constrained to the sharded layout → XLA emits
           reduce-scatter instead of all-reduce (the ``average_tensor``
           hot loop, stage_1_and_2.py:1159)
  stage 3: + parameters sharded over ``data``; XLA inserts all-gathers at
           each use and its latency-hiding scheduler overlaps them with
           compute (replacing fetch/release hooks + prefetching,
           partitioned_param_coordinator.py:285)

Persistence threshold (`param_persistence_threshold`, stage3.py): leaves with
fewer elements stay replicated — same memory/latency trade the reference
makes for small params.

Sharding rule per leaf: place ``data`` on the largest dimension divisible by
the data-axis size that is not already taken by a model/expert/sequence axis
from tensor-parallel sharding rules (``base_specs``).
"""

from dataclasses import dataclass
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu._jax_compat import host_memory_kind
from deepspeed_tpu.parallel.topology import DATA_AXIS, ZERO_AXES, Topology


def _spec_axes(spec: Optional[PartitionSpec]):
    """Set of mesh-axis names already used by a PartitionSpec."""
    used = set()
    if spec is None:
        return used
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


def choose_zero_spec(
    shape,
    axis_size: int,
    base_spec: Optional[PartitionSpec] = None,
    axes=(DATA_AXIS,),
) -> PartitionSpec:
    """Add the ZeRO axes (``data``/``zero`` or a MiCS subset) to a leaf's
    PartitionSpec on the best free dim. ``axis_size`` is the product of the
    participating axis sizes; trivial (size-1) axes are dropped from the
    placement so specs stay readable."""
    axes = tuple(a for a in axes)
    if axis_size <= 1:
        return base_spec if base_spec is not None else PartitionSpec()
    placement = axes[0] if len(axes) == 1 else tuple(axes)
    base = tuple(base_spec) if base_spec is not None else ()
    base = base + (None,) * (len(shape) - len(base))
    used = _spec_axes(base_spec)
    if any(a in used for a in axes):
        return PartitionSpec(*base)
    # candidate dims: unsharded by base spec and divisible by axis_size
    best_dim, best_size = None, 0
    for i, d in enumerate(shape):
        taken = i < len(base) and base[i] is not None
        if taken:
            # dim already sharded by e.g. model axis; data can nest with it
            # only if the residual size divides. Handled below via tuple merge.
            continue
        if d % axis_size == 0 and d > best_size:
            best_dim, best_size = i, d
    if best_dim is None:
        # try nesting the zero axes inside an already-sharded dim
        for i, d in enumerate(shape):
            if i < len(base) and base[i] is not None:
                prev = base[i] if isinstance(base[i], tuple) else (base[i],)
                if not any(a in prev for a in axes) and d % (axis_size * _axes_product(prev)) == 0:
                    new = list(base)
                    new[i] = tuple(prev) + axes
                    return PartitionSpec(*new)
        return PartitionSpec(*base)  # replicated over data (e.g. odd-shaped scalars)
    new = list(base)
    new[best_dim] = placement
    return PartitionSpec(*new)


def _axes_product(axes):
    from deepspeed_tpu.parallel.topology import get_topology

    topo = get_topology()
    out = 1
    for a in axes:
        out *= topo.axis_size(a)
    return out


@dataclass
class ZeroShardingPlan:
    """Per-pytree NamedShardings implementing a ZeRO stage."""

    stage: int
    topology: Topology
    param_shardings: Any  # how model (half) params live
    grad_shardings: Any  # constraint applied to grads before the optimizer
    master_shardings: Any  # fp32 master + optimizer moments
    param_specs: Any
    grad_specs: Any
    master_specs: Any
    persistence_threshold: int = 0
    # ZeRO-Offload tiers (reference offload_config.py): state/params live in
    # host memory ("pinned_host" memory kind) instead of HBM
    offload_optimizer: bool = False
    offload_param: bool = False
    # Twin-Flow partial offload (reference engine.py:921): fraction of
    # optimizer-state BYTES placed host-side; largest leaves offload first so
    # the fewest leaves pay the transfer. 1.0 = everything offloads.
    offload_ratio: float = 1.0
    # MiCS/hpZ: which mesh axes params vs optimizer state shard over
    # (ZERO_AXES = full dp; ("zero",) = within the shard group only)
    param_zero_axes: tuple = ZERO_AXES
    state_zero_axes: tuple = ZERO_AXES

    @property
    def state_memory_kind(self):
        return host_memory_kind() if self.offload_optimizer else None

    @property
    def param_memory_kind(self):
        return host_memory_kind() if self.offload_param else None

    def device_shardings(self, shardings):
        """The HBM-resident twin of a (possibly host-kind) sharding tree —
        used to stage offloaded state onto the chip around the update. No
        explicit memory kind: the default is device memory, and kind-less
        shardings avoid placement annotations that the CPU backend's SPMD
        partitioner rejects on scalars."""
        return jax.tree.map(
            lambda s: NamedSharding(s.mesh, s.spec),
            shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding),
        )

    def state_shardings(self, state_shape_tree):
        """Shardings for an optimizer-state pytree (from ``jax.eval_shape`` of
        ``opt.init``). Optimizer moments mirror param shapes, so each array
        leaf gets the stage's master sharding rule applied to its own shape;
        scalars (step counts) are replicated. This is how the reference's
        per-partition optimizer state (stage_1_and_2.py ``single_partition_of_
        fp32_groups``) falls out of the sharding rule for free."""
        axes = tuple(a for a in self.state_zero_axes if self.topology.axis_size(a) > 1)
        axis_size = 1
        for a in axes:
            axis_size *= self.topology.axis_size(a)
        mesh = self.topology.mesh
        stage = self.stage

        kind = self.state_memory_kind
        # Twin-Flow: offload only `offload_ratio` of the state bytes —
        # largest leaves first — leaving the rest in HBM
        host_leaf = self._partial_offload_mask(state_shape_tree) if kind else None

        def leaf_sharding(leaf, offloaded=True):
            shape = tuple(getattr(leaf, "shape", ()))
            if stage >= 1 and shape:
                spec = choose_zero_spec(shape, axis_size, None, axes=axes or (DATA_AXIS,))
            else:
                spec = PartitionSpec()
            # scalars (step counts) stay in device memory: XLA's SPMD
            # partitioner rejects host-placement annotations on scalar
            # side-effect custom-calls, and 4 bytes buys nothing offloaded
            if kind is not None and shape and offloaded:
                return NamedSharding(mesh, spec, memory_kind=kind)
            return NamedSharding(mesh, spec)

        if host_leaf is None:
            return jax.tree.map(leaf_sharding, state_shape_tree)
        return jax.tree.map(leaf_sharding, state_shape_tree, host_leaf)

    def _partial_offload_mask(self, state_shape_tree):
        """Boolean-per-leaf tree: True = leaf lives host-side. Greedy by
        descending size until ``offload_ratio`` of total bytes is host-bound."""
        flat, treedef = jax.tree_util.tree_flatten(state_shape_tree)
        sizes = [
            int(np.prod(getattr(l, "shape", ()) or (1,)))
            * np.dtype(getattr(l, "dtype", np.float32)).itemsize
            for l in flat
        ]
        if self.offload_ratio >= 1.0:
            return jax.tree_util.tree_unflatten(treedef, [True] * len(flat))
        budget = self.offload_ratio * sum(sizes)
        mask = [False] * len(flat)
        cum = 0
        for i in sorted(range(len(flat)), key=lambda j: -sizes[j]):
            if cum >= budget:
                break
            mask[i] = True
            cum += sizes[i]
        return jax.tree_util.tree_unflatten(treedef, mask)


def build_zero_plan(
    stage: int,
    topology: Topology,
    params: Any,
    persistence_threshold: int = 0,
    base_specs: Any = None,
    zero_axes=ZERO_AXES,
    param_zero_axes=None,
    offload_optimizer: bool = False,
    offload_param: bool = False,
    offload_ratio: float = 1.0,
) -> ZeroShardingPlan:
    """Construct the stage's sharding plan over a params pytree.

    ``base_specs`` optionally carries tensor/expert-parallel PartitionSpecs
    per leaf (the AutoTP analogue); ZeRO composes with them by choosing a
    free dimension. ``zero_axes`` shard optimizer state + gradients;
    ``param_zero_axes`` (default = same) shard the parameters — MiCS/hpZ
    restrict it to the ``zero`` shard-group axis so param gathers stay
    intra-group while grads still reduce over the whole dp world.
    """
    if param_zero_axes is None:
        param_zero_axes = zero_axes

    def live(axes):
        return tuple(a for a in axes if topology.axis_size(a) > 1)

    def size_of(axes):
        out = 1
        for a in axes:
            out *= topology.axis_size(a)
        return out

    state_axes = live(zero_axes)
    param_axes = live(param_zero_axes)
    state_size = size_of(state_axes)
    param_size = size_of(param_axes)
    mesh = topology.mesh

    flat_params, treedef = jax.tree_util.tree_flatten(params)
    if base_specs is None:
        flat_base = [None] * len(flat_params)
    else:
        # base_specs mirrors the params structure with PartitionSpec/None leaves
        flat_base = treedef.flatten_up_to(base_specs)

    def leaf_shape(p):
        return tuple(p.shape) if hasattr(p, "shape") else ()

    def strip_trivial(base):
        """Drop size-1 mesh axes from a base spec so the dims they nominally
        occupy stay candidates for the zero axes. Without this an embed table
        with base P('model', None) under model=1 pushes the zero shard onto
        the hidden dim — and the backward scatter-add then reshards the
        batch-sharded cotangent to hidden-sharded via an involuntary full
        rematerialization (whole-tensor replication per step)."""
        if base is None:
            return None
        out = []
        for e in tuple(base):
            if isinstance(e, tuple):
                kept = tuple(a for a in e if topology.axis_size(a) > 1)
                out.append(kept if len(kept) > 1 else (kept[0] if kept else None))
            elif e is None or topology.axis_size(e) > 1:
                out.append(e)
            else:
                out.append(None)
        return PartitionSpec(*out)

    def sharded_spec(axes, axis_size):
        def fn(p, base, threshold=0):
            shape = leaf_shape(p)
            n = int(np.prod(shape)) if shape else 1
            if n < threshold or not shape:
                return PartitionSpec(*base) if base is not None else PartitionSpec()
            return choose_zero_spec(
                shape, axis_size, strip_trivial(base), axes=axes or (DATA_AXIS,)
            )

        return fn

    def base_or_replicated(p, base, threshold=0):
        return PartitionSpec(*base) if base is not None else PartitionSpec()

    def build(spec_fn, threshold=0):
        return jax.tree_util.tree_unflatten(
            treedef, [spec_fn(p, b, threshold) for p, b in zip(flat_params, flat_base)]
        )

    # persistence threshold applies to *params* only (reference
    # param_persistence_threshold); optimizer state and gradients always
    # partition at their stage.
    param_specs = build(
        sharded_spec(param_axes, param_size) if stage >= 3 else base_or_replicated,
        persistence_threshold,
    )
    grad_specs = build(sharded_spec(state_axes, state_size) if stage >= 2 else base_or_replicated)
    master_specs = build(sharded_spec(state_axes, state_size) if stage >= 1 else base_or_replicated)

    def to_sharding(kind):
        if kind is None:
            return lambda spec: NamedSharding(mesh, spec)
        return lambda spec: NamedSharding(mesh, spec, memory_kind=kind)

    is_spec = lambda x: isinstance(x, PartitionSpec)
    param_kind = host_memory_kind() if offload_param else None
    master_kind = host_memory_kind() if offload_optimizer else None
    return ZeroShardingPlan(
        stage=stage,
        topology=topology,
        param_shardings=jax.tree.map(to_sharding(param_kind), param_specs, is_leaf=is_spec),
        grad_shardings=jax.tree.map(to_sharding(None), grad_specs, is_leaf=is_spec),
        master_shardings=jax.tree.map(to_sharding(master_kind), master_specs, is_leaf=is_spec),
        param_specs=param_specs,
        grad_specs=grad_specs,
        master_specs=master_specs,
        persistence_threshold=persistence_threshold,
        offload_optimizer=offload_optimizer,
        offload_param=offload_param,
        offload_ratio=offload_ratio,
        param_zero_axes=tuple(param_zero_axes),
        state_zero_axes=tuple(zero_axes),
    )


def constrain_tree(tree, specs, mesh):
    """with_sharding_constraint over a pytree (the stage-2 reduce-scatter
    trigger and stage-3 repartition point)."""
    from jax.lax import with_sharding_constraint

    is_spec = lambda x: isinstance(x, PartitionSpec)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs, is_leaf=is_spec)
    return jax.tree.map(
        lambda x, s: with_sharding_constraint(x, s),
        tree,
        shardings,
        is_leaf=lambda x: hasattr(x, "shape") and not isinstance(x, NamedSharding),
    )
