"""zero.Init — deferred, directly-sharded parameter construction.

Analogue of the reference ``zero.Init`` context
(``runtime/zero/partition_parameters.py:878``): there, ``nn.Module.__init__``
is patched so every parameter is partitioned the moment it is constructed,
letting models larger than a single host's memory be built. The functional
JAX form: the user hands ``initialize()`` an *init function* instead of a
materialized pytree; the engine evaluates its shapes abstractly
(``jax.eval_shape``), builds the ZeRO sharding plan from those shapes, and
materializes by running the init function under ``jax.jit`` with the plan's
``out_shardings`` — every device computes/receives only its own shard, and
the full parameter pytree never exists on any single host or device.

Usage::

    def build_params():
        return init_params(cfg, jax.random.key(0))

    engine, *_ = deepspeed_tpu.initialize(
        model=make_loss_fn(cfg),
        model_parameters=zero.Init(build_params),  # or just build_params
        config={...,"zero_optimization": {"stage": 3}},
    )

A bare zero-argument callable works too; ``zero.Init`` adds reference-API
parity plus optional dtype/rng plumbing.
"""

from typing import Any, Callable, Optional

import jax


class Init:
    """Marker wrapping a parameter init function for deferred construction.

    fn:    zero-argument callable returning the params pytree (close over
           your config/rng), or one taking ``rng`` when ``rng`` is given.
    rng:   optional PRNG key passed to ``fn``.
    """

    def __init__(self, fn: Callable[..., Any], rng: Optional[jax.Array] = None):
        if not callable(fn):
            raise TypeError(f"zero.Init needs a callable init function, got {type(fn)}")
        self.fn = fn
        self.rng = rng

    def make_init_fn(self) -> Callable[[], Any]:
        if self.rng is not None:
            rng = self.rng
            return lambda: self.fn(rng)
        return self.fn


def as_deferred_init(model_parameters) -> Optional[Callable[[], Any]]:
    """Recognize a deferred-init request: a ``zero.Init`` marker or a bare
    callable (pytrees of arrays are not callable). Returns the zero-arg init
    fn, or None for eager (materialized) parameters."""
    if isinstance(model_parameters, Init):
        return model_parameters.make_init_fn()
    if callable(model_parameters) and not hasattr(model_parameters, "shape"):
        return model_parameters
    return None
