"""TiledLinear: tile a huge linear's dims so only active tiles materialize.

Analogue of the reference ``runtime/zero/tiling.py:32 TiledLinear``: the
weight splits into an (in_splits × out_splits) grid processed sequentially —
with ZeRO-3/offload, inactive tiles stay partitioned/offloaded, bounding
peak memory by one tile. Functional form: the tiles ARE the params (a
[in_splits, out_splits, tile_in, tile_out] stack the ZeRO plan shards like
any leaf), and the matmul scans the grid accumulating partial products.
"""

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


def init_tiled_linear(
    key: jax.Array,
    in_features: int,
    out_features: int,
    in_splits: int = 1,
    out_splits: int = 1,
    bias: bool = True,
    dtype=jnp.float32,
    weight: Optional[jax.Array] = None,
) -> Dict[str, Any]:
    if in_features % in_splits != 0 or out_features % out_splits != 0:
        raise ValueError(
            f"tiled linear needs divisible splits: in {in_features}/{in_splits}, "
            f"out {out_features}/{out_splits}")
    ti, to = in_features // in_splits, out_features // out_splits
    if weight is None:
        weight = jax.random.normal(key, (in_features, out_features), jnp.float32) * (
            in_features**-0.5
        )
    tiles = (
        weight.reshape(in_splits, ti, out_splits, to).transpose(0, 2, 1, 3).astype(dtype)
    )  # [in_splits, out_splits, ti, to]
    out = {"tiles": tiles}
    if bias:
        out["bias"] = jnp.zeros((out_features,), dtype)
    return out


def tiled_linear(params: Dict[str, Any], x: jax.Array) -> jax.Array:
    """y = x @ W + b over the tile grid: scan over in_splits accumulating
    into [.., out] so at most one [ti, out_splits*to] row of tiles is live."""
    tiles = params["tiles"]  # [I, O, ti, to]
    I, O, ti, to = tiles.shape
    xt = x.reshape(x.shape[:-1] + (I, ti))

    def body(acc, io):
        x_i, row = io  # x_i: [.., ti]; row: [O, ti, to]
        part = jnp.einsum("...i,oid->...od", x_i, row)
        return acc + part.reshape(part.shape[:-2] + (O * to,)), None

    x_scan = jnp.moveaxis(xt, -2, 0)  # [I, .., ti]
    # carry dtype must match the einsum result (bf16 activations over fp32
    # master tiles promote to fp32)
    acc0 = jnp.zeros(x.shape[:-1] + (O * to,), jnp.result_type(x.dtype, tiles.dtype))
    acc, _ = jax.lax.scan(body, acc0, (x_scan, tiles))
    if "bias" in params:
        acc = acc + params["bias"]
    return acc


def tiled_linear_weight(params: Dict[str, Any]) -> jax.Array:
    """Reassemble the dense [in, out] weight (export/debug)."""
    tiles = params["tiles"]
    I, O, ti, to = tiles.shape
    return tiles.transpose(0, 2, 1, 3).reshape(I * ti, O * to)
