"""ZeRO configuration.

TPU-native analogue of the reference ``runtime/zero/config.py``
(``DeepSpeedZeroConfig`` :89) and ``runtime/zero/offload_config.py``
(``DeepSpeedZeroOffloadParamConfig`` :14, ``DeepSpeedZeroOffloadOptimizerConfig``
:21).

On TPU, stages map to sharding policies over the ``data`` mesh axis:
  stage 0 — replicated params/grads/optimizer state (plain DP; XLA psum)
  stage 1 — optimizer state sharded over data axis
  stage 2 — + gradients reduce-scattered (sharding constraint on grads)
  stage 3 — + parameters sharded (XLA GSPMD inserts all-gathers, overlapped
            by the latency-hiding scheduler — the compiler plays the role of
            the reference's partitioned_param_coordinator prefetching)
"""

from dataclasses import dataclass, field
from typing import Optional

from deepspeed_tpu.runtime.config_utils import ConfigError, DSConfigModel, submodel

ZERO_OPTIMIZATION = "zero_optimization"


class OffloadDeviceEnum:
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


@dataclass
class DeepSpeedZeroOffloadParamConfig(DSConfigModel):
    """Parameter offload (reference offload_config.py:14)."""

    device: str = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False

    def _validate(self):
        if self.device not in (OffloadDeviceEnum.none, OffloadDeviceEnum.cpu, OffloadDeviceEnum.nvme):
            raise ConfigError(f"Invalid offload device {self.device}")


@dataclass
class DeepSpeedZeroOffloadOptimizerConfig(DSConfigModel):
    """Optimizer offload (reference offload_config.py:21)."""

    device: str = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    # Twin-Flow partial offload (reference engine.py:921 zero_partial_offload):
    # fraction of optimizer-state bytes placed in host memory; the rest stays
    # in HBM so only `ratio` of the state crosses the link each step
    ratio: float = 1.0
    # SuperOffload (reference engine.py:924 + superoffload_stage3.py): run the
    # whole optimizer host-side against RAM-resident state via CPU-Adam
    super_offload: bool = False
    cpuadam_cores_perc: float = 0.8
    # weight_stream tier: store/stream the Adam moments as int8 blocks with
    # fp32 per-256-block scales (ZeRO++ quantized exchange applied to the
    # ZeRO-Infinity swap traffic — reference stage3.py:1610
    # quantize_nontrainable_params + partitioned_optimizer_swapper). The
    # streamed step is wire-limited; bytes are the lever (PERF.md
    # streamed-7B roofline). 0 = fp32 state (default), 8 = int8 moments.
    stream_quant_bits: int = 0

    def _validate(self):
        if self.device not in (OffloadDeviceEnum.none, OffloadDeviceEnum.cpu, OffloadDeviceEnum.nvme):
            raise ConfigError(f"Invalid offload device {self.device}")
        if not 0.0 <= self.ratio <= 1.0:
            raise ConfigError("offload_optimizer.ratio must be in [0, 1]")


@dataclass
class DeepSpeedZeroConfig(DSConfigModel):
    """``zero_optimization`` section (reference runtime/zero/config.py:89).

    Knobs that exist purely to tune manual CUDA bucketing/overlap are accepted
    for config compatibility but are no-ops on TPU, where XLA handles
    bucketing/fusion/overlap; they are marked [compat] below.
    """

    stage: int = 0
    contiguous_gradients: bool = True  # [compat]
    reduce_scatter: bool = True  # [compat] — always reduce-scatter on TPU for stage>=2
    # grad reduce-scatter bucket target (bytes): leaves are grouped into
    # buckets of this size and each bucket crosses the wire in ONE
    # collective, launched independently so the scheduler can pipeline them
    # behind remaining backward compute (runtime/zero/overlap.py)
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True  # [compat]
    allgather_bucket_size: int = 500_000_000  # [compat]
    # Bucketed comm/compute overlap (reference overlap_comm + the stage-3
    # prefetch coordinator): None = auto (ON — the overlapped and
    # unoverlapped paths are loss-bitwise identical), False = escape hatch
    # forcing the per-leaf/serial schedule, True = explicit opt-in.
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    # Offload
    offload_param: DeepSpeedZeroOffloadParamConfig = submodel(DeepSpeedZeroOffloadParamConfig)
    offload_optimizer: DeepSpeedZeroOffloadOptimizerConfig = submodel(DeepSpeedZeroOffloadOptimizerConfig)
    # Stage-3 specifics
    sub_group_size: int = 1_000_000_000
    max_live_parameters: int = 1_000_000_000  # [compat]
    max_reuse_distance: int = 1_000_000_000  # [compat]
    # parameter-prefetch window (bytes): bounds how many layers' worth of
    # gathered/staged weights sit in HBM ahead of the layer being computed
    # (transformer scan chunking — overlap.overlap_chunk) and the qwZ
    # gather bucket target. ``stage3_prefetch_bucket_size`` is the
    # reference's spelling for the same knob and takes precedence when set.
    prefetch_bucket_size: int = 50_000_000
    stage3_prefetch_bucket_size: Optional[int] = None
    param_persistence_threshold: int = 100_000  # params smaller than this stay replicated
    model_persistence_threshold: int = 9223372036854775807
    gather_16bit_weights_on_model_save: bool = False
    stage3_gather_16bit_weights_on_model_save: bool = False
    ignore_unused_parameters: bool = True
    round_robin_gradients: bool = False  # [compat]
    # ZeRO++ (hpZ / qwZ / qgZ — reference engine.py:1085-1097)
    zero_hpz_partition_size: int = 1
    zero_quantized_weights: bool = False
    zero_quantized_nontrainable_weights: bool = False
    zero_quantized_gradients: bool = False
    zeropp_loco_param: Optional[dict] = None
    # MiCS
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False
    memory_efficient_linear: bool = True
    pipeline_loading_checkpoint: bool = False
    override_module_apply: bool = True
    log_trace_cache_warnings: bool = False

    @property
    def overlap_enabled(self) -> bool:
        """overlap_comm resolved: None (auto) and True → on; False → off."""
        return self.overlap_comm is not False

    @property
    def effective_prefetch_bucket_size(self) -> int:
        if self.stage3_prefetch_bucket_size is not None:
            return int(self.stage3_prefetch_bucket_size)
        return int(self.prefetch_bucket_size)

    def _validate(self):
        if not 0 <= self.stage <= 3:
            raise ConfigError(f"ZeRO stage must be 0-3, got {self.stage}")
        if self.zero_hpz_partition_size < 1:
            raise ConfigError("zero_hpz_partition_size must be >= 1")
        if self.reduce_bucket_size <= 0:
            raise ConfigError("reduce_bucket_size must be > 0")
        if self.effective_prefetch_bucket_size <= 0:
            raise ConfigError("prefetch_bucket_size must be > 0")
