"""Bucketed comm/compute overlap for the ZeRO-3 step.

The reference DeepSpeed hides ZeRO-3 communication behind compute with three
hand-rolled schedulers: the partitioned-param coordinator prefetches
all-gathers ``stage3_prefetch_bucket_size`` bytes ahead, gradient
reduce-scatters launch per ``reduce_bucket_size`` bucket as backward produces
them (stage3.py ``__reduce_and_partition_ipg_grads``), and ZeRO-Infinity
double-buffers the NVMe/host weight windows. On TPU the XLA latency-hiding
scheduler can do the overlap — but only when the program hands it
independent collectives to move. This module restructures the step so it
does:

* ``assign_buckets`` groups leaves into size-targeted buckets (every leaf in
  exactly one bucket, greedy in traversal order — the reference's
  ``reduce_bucket_size`` semantics).
* The ``bucketed_*`` collectives fuse each bucket's per-leaf exchanges into
  ONE wire collective (payloads concatenated along the block axis). Each
  leaf is quantized/laid out exactly as the per-leaf functions in
  ``ops/quantizer/block_quant.py`` do, so results are BITWISE identical to
  the unbucketed path — the escape hatch (``overlap_comm: false``) and the
  default path must produce the same losses. Fewer, larger collectives give
  the scheduler long independent transfers to pipeline behind compute
  instead of a serial chain of per-leaf launches.
* ``overlap_chunk`` picks the transformer-scan chunk width for bucketed
  parameter prefetch: scanning ``B`` layers per step puts layer ``b+1``'s
  all-gather (or pinned_host→HBM stage) in the SAME scan body as layer
  ``b``'s compute, where the scheduler can overlap them — impossible across
  sequential scan iterations.
* ``tiles > 1`` (the ``comm_overlap: tiled`` seam, ``comm/overlap_tiled.py``)
  further splits each bucket's fused payload into up to ``tiles`` contiguous
  column chunks and fires one all-gather per chunk from a Python loop — the
  chunks are independent HLO peers (no loop carry), so parameter tiles
  stream in behind the transformer scan's GEMM slices instead of arriving
  bucket-at-a-time. All-gather is pure transport (no reduction order), so
  the tiled result is BITWISE identical to the monolithic gather; the
  quantized form splits on block boundaries so each chunk dequantizes
  exactly as its slice of the fused exchange.

All ``bucketed_*`` functions must be called INSIDE ``shard_map`` over
``axis_name`` (same contract as their per-leaf counterparts).
"""

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer.block_quant import _dequantize_rows, _quantize_rows

__all__ = [
    "assign_buckets",
    "overlap_chunk",
    "bucketed_all_gather",
    "bucketed_psum_scatter",
    "bucketed_quantized_all_gather",
    "bucketed_quantized_reduce_scatter",
    "bucketed_loco_quantized_reduce_scatter",
]


def assign_buckets(sizes: Sequence[int], target_bytes: int) -> List[List[int]]:
    """Greedy size-targeted bucketing (reference ``reduce_bucket_size``):
    walk ``sizes`` in order, close the current bucket when adding the next
    leaf would exceed ``target_bytes`` (a leaf larger than the target gets a
    bucket of its own). Every index lands in exactly one bucket; order is
    preserved so bucket k's exchange depends only on leaves before bucket
    k+1's — the property the scheduler needs to pipeline them."""
    if target_bytes <= 0:
        return [[i] for i in range(len(sizes))]
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, sz in enumerate(sizes):
        if cur and cur_bytes + sz > target_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += sz
    if cur:
        buckets.append(cur)
    return buckets


def overlap_chunk(n_layers: int, layer_bytes: int, target_bytes: int,
                  max_chunk: int = 8) -> int:
    """Scan-chunk width for bucketed parameter prefetch: the largest divisor
    ``B`` of ``n_layers`` with ``B * layer_bytes <= target_bytes`` — i.e. at
    most one prefetch bucket of layer weights live in HBM beyond the layer
    being computed. Floors at 2 when any >=2 divisor exists (depth-1
    prefetch is the point of overlap; the knob then only grows the window)
    and caps at ``max_chunk`` (chunking unrolls the scan body B-fold —
    compile time, not memory, bounds the useful width). Returns 1 when no
    divisor works (prime depth): the caller falls back to the plain scan."""
    if n_layers <= 1 or layer_bytes <= 0:
        return 1
    divisors = [d for d in range(2, min(n_layers, max_chunk) + 1) if n_layers % d == 0]
    if not divisors:
        return 1
    fitting = [d for d in divisors if d * layer_bytes <= target_bytes]
    return max(fitting) if fitting else divisors[0]


# ---------------------------------------------------------------------------
# bucketed wire collectives (shard_map manual region)
# ---------------------------------------------------------------------------
def _tile_bounds(n_cols: int, tiles: int, quantum: int = 1) -> List[int]:
    """Column boundaries splitting ``[0, n_cols)`` into at most ``tiles``
    contiguous chunks, each a ``quantum``-column multiple (``quantum`` =
    ``block_size`` for quantized payloads so every chunk dequantizes on
    block boundaries). ``n_cols`` must itself be a quantum multiple.
    Uneven remainders spread one quantum at a time over the leading chunks;
    fewer units than tiles just yields fewer chunks — there is no fallback
    to untiled because any contiguous split is transport-identical."""
    units = n_cols // quantum
    t = max(1, min(int(tiles), units))
    base, extra = divmod(units, t)
    bounds = [0]
    for i in range(t):
        bounds.append(bounds[-1] + (base + (1 if i < extra else 0)) * quantum)
    return bounds


def _record_gather_wire(tag: str, quant_bytes: int, leaves, tiles: int) -> None:
    """Fold one traced bucket gather into the shared wire registry
    (``comm.quantized.record_wire``) so ``wire_stats()`` shows the ZeRO-3
    prefetch wire next to the serving wires — including its tile-granular
    overlap factor. ``fp_bytes`` is what the unquantized fused gather would
    put on the wire (the local concat payload at leaf dtype width)."""
    from deepspeed_tpu.comm.quantized import record_wire

    fp_bytes = sum(int(x.size) * x.dtype.itemsize for x in leaves)
    record_wire(tag, int(quant_bytes), int(fp_bytes), tiles=tiles)


def _rows_for_scatter(x: jax.Array, dim: int, W: int, block_size: int):
    """Per-leaf reduce-scatter layout — identical to
    ``quantized_reduce_scatter_along``: moveaxis ``dim``→0, reshape to
    [W, m] (row w is rank w's shard), pad the row to ``block_size``."""
    moved = jnp.moveaxis(x.astype(jnp.float32), dim, 0)
    rows = moved.reshape(W, -1)
    m = rows.shape[1]
    pad = (-m) % block_size
    if pad:
        rows = jnp.pad(rows, ((0, 0), (0, pad)))
    return rows, m, moved.shape[1:]


def _unscatter(total: jax.Array, x: jax.Array, dim: int, W: int, rest_shape,
               mean: bool) -> jax.Array:
    D = x.shape[dim]
    if mean:
        total = total / W
    out = total.reshape((D // W,) + tuple(rest_shape))
    return jnp.moveaxis(out, 0, dim).astype(x.dtype)


def bucketed_quantized_reduce_scatter(
    leaves: Sequence[jax.Array],
    dims: Sequence[int],
    axis_name: str,
    bits: int = 8,
    block_size: int = 256,
    mean: bool = True,
) -> List[jax.Array]:
    """One bucket's qgZ exchange: each leaf quantized exactly as
    ``quantized_reduce_scatter_along`` (same row layout, same per-leaf
    blocking), payloads+scales concatenated along the BLOCK axis so the
    bucket crosses the wire in ONE all-to-all pair. Splitting the received
    concat recovers each leaf's per-leaf exchange bitwise."""
    W = jax.lax.axis_size(axis_name)
    payloads, scales, metas = [], [], []
    for x, k in zip(leaves, dims):
        rows, m, rest = _rows_for_scatter(x, k, W, block_size)
        p, s = _quantize_rows(rows, bits, block_size)
        payloads.append(p)
        scales.append(s)
        metas.append((m, rest, p.shape[1]))
    payload_rx = jax.lax.all_to_all(
        jnp.concatenate(payloads, axis=1), axis_name,
        split_axis=0, concat_axis=0, tiled=True,
    )
    scales_rx = jax.lax.all_to_all(
        jnp.concatenate(scales, axis=1), axis_name,
        split_axis=0, concat_axis=0, tiled=True,
    )
    out, off = [], 0
    for x, k, (m, rest, nb) in zip(leaves, dims, metas):
        deq = _dequantize_rows(
            payload_rx[:, off:off + nb], scales_rx[:, off:off + nb], bits, block_size
        )
        total = jnp.sum(deq, axis=0)[:m]
        out.append(_unscatter(total, x, k, W, rest, mean))
        off += nb
    return out


def bucketed_loco_quantized_reduce_scatter(
    leaves: Sequence[jax.Array],
    errs: Sequence[jax.Array],
    dims: Sequence[int],
    axis_name: str,
    bits: int = 8,
    block_size: int = 256,
    err_beta: float = 0.8,
    mean: bool = True,
):
    """LoCo error-feedback variant: the compensated gradient ``x + err`` is
    quantized per leaf (identical to ``loco_quantized_reduce_scatter_along``
    including the local pre-exchange residual and the EMA update), only the
    all-to-all pair is fused across the bucket. Returns
    (reduced slices, new error buffers)."""
    W = jax.lax.axis_size(axis_name)
    payloads, scales, metas, new_errs = [], [], [], []
    for x, err, k in zip(leaves, errs, dims):
        comp = x.astype(jnp.float32) + err.astype(jnp.float32)
        moved = jnp.moveaxis(comp, k, 0)
        rest = moved.shape[1:]
        rows = moved.reshape(W, -1)
        m = rows.shape[1]
        pad = (-m) % block_size
        if pad:
            rows = jnp.pad(rows, ((0, 0), (0, pad)))
        p, s = _quantize_rows(rows, bits, block_size)
        deq_local = _dequantize_rows(p, s, bits, block_size)
        resid = (rows - deq_local)[:, :m].reshape((x.shape[k],) + rest)
        resid = jnp.moveaxis(resid, 0, k)
        new_errs.append(
            (err_beta * err.astype(jnp.float32) + (1.0 - err_beta) * resid)
            .astype(err.dtype)
        )
        payloads.append(p)
        scales.append(s)
        metas.append((m, rest, p.shape[1]))
    payload_rx = jax.lax.all_to_all(
        jnp.concatenate(payloads, axis=1), axis_name,
        split_axis=0, concat_axis=0, tiled=True,
    )
    scales_rx = jax.lax.all_to_all(
        jnp.concatenate(scales, axis=1), axis_name,
        split_axis=0, concat_axis=0, tiled=True,
    )
    out, off = [], 0
    for x, k, (m, rest, nb) in zip(leaves, dims, metas):
        deq = _dequantize_rows(
            payload_rx[:, off:off + nb], scales_rx[:, off:off + nb], bits, block_size
        )
        total = jnp.sum(deq, axis=0)[:m]
        out.append(_unscatter(total, x, k, W, rest, mean))
        off += nb
    return out, new_errs


def bucketed_quantized_all_gather(
    leaves: Sequence[jax.Array],
    dims: Sequence[int],
    axis_name: str,
    bits: int = 8,
    block_size: int = 256,
    tiles: int = 1,
) -> List[jax.Array]:
    """One bucket's qwZ gather: per-leaf quantization identical to
    ``quantized_all_gather_along`` ([1, m] local rows), payloads fused into
    one all-gather pair along the block axis. ``tiles > 1`` splits the fused
    payload on block boundaries into independent per-tile all-gather pairs
    (see module docstring); the reassembled planes are bitwise identical to
    the monolithic exchange, so dequantization is unchanged."""
    payloads, scales, metas = [], [], []
    for x, k in zip(leaves, dims):
        moved = jnp.moveaxis(x, k, 0)
        rows = moved.reshape(1, -1).astype(jnp.float32)
        m = rows.shape[1]
        pad = (-m) % block_size
        if pad:
            rows = jnp.pad(rows, ((0, 0), (0, pad)))
        p, s = _quantize_rows(rows, bits, block_size)
        payloads.append(p)
        scales.append(s)
        metas.append((m, moved.shape, p.shape[1]))
    payload_cat = jnp.concatenate(payloads, axis=1)
    scales_cat = jnp.concatenate(scales, axis=1)
    # axis 1 of both planes is the BLOCK axis (one unit per block_size
    # chunk), so block-aligned tiling is just a contiguous index split —
    # payload and scales share the same boundaries
    pb = _tile_bounds(payload_cat.shape[1], tiles)
    _record_gather_wire(
        "zero3_gather",
        int(payload_cat.size) * payload_cat.dtype.itemsize
        + int(scales_cat.size) * scales_cat.dtype.itemsize,
        leaves,
        tiles=len(pb) - 1,
    )
    if len(pb) > 2:
        payload_all = jnp.concatenate(
            [
                jax.lax.all_gather(
                    payload_cat[:, pb[i]:pb[i + 1]], axis_name, axis=0, tiled=True
                )
                for i in range(len(pb) - 1)
            ],
            axis=1,
        )
        scales_all = jnp.concatenate(
            [
                jax.lax.all_gather(
                    scales_cat[:, pb[i]:pb[i + 1]], axis_name, axis=0, tiled=True
                )
                for i in range(len(pb) - 1)
            ],
            axis=1,
        )
    else:
        payload_all = jax.lax.all_gather(payload_cat, axis_name, axis=0, tiled=True)
        scales_all = jax.lax.all_gather(scales_cat, axis_name, axis=0, tiled=True)
    W = payload_all.shape[0]
    out, off = [], 0
    for x, k, (m, moved_shape, nb) in zip(leaves, dims, metas):
        deq = _dequantize_rows(
            payload_all[:, off:off + nb], scales_all[:, off:off + nb], bits, block_size
        )
        full = deq[:, :m].reshape((W * moved_shape[0],) + tuple(moved_shape[1:]))
        out.append(jnp.moveaxis(full, 0, k).astype(x.dtype))
        off += nb
    return out


def bucketed_all_gather(
    leaves: Sequence[jax.Array],
    dims: Sequence[int],
    axis_name: str,
    tiles: int = 1,
) -> List[jax.Array]:
    """Unquantized bucket gather: each local shard flattened to [1, m]
    (leading axis = gather dim, so rank r's row chunk IS its dim-k slice),
    concatenated and gathered in ONE collective, then split and restored —
    value-identical to per-leaf ``jax.lax.all_gather(..., tiled=True)``.
    ``tiles > 1`` fires one all-gather per contiguous column chunk instead
    (independent HLO peers, see module docstring) — pure transport, so
    reassembly is bitwise identical to the monolithic gather."""
    flats, metas = [], []
    for x, k in zip(leaves, dims):
        moved = jnp.moveaxis(x, k, 0)
        flats.append(moved.reshape(1, -1))
        metas.append((moved.shape, moved.size))
    widths = {f.dtype for f in flats}
    if len(widths) != 1:
        raise ValueError("bucket leaves must share a dtype")
    concat = jnp.concatenate(flats, axis=1)
    tb = _tile_bounds(concat.shape[1], tiles)
    _record_gather_wire(
        "zero3_gather",
        int(concat.size) * concat.dtype.itemsize,
        leaves,
        tiles=len(tb) - 1,
    )
    if len(tb) > 2:
        gathered = jnp.concatenate(
            [
                jax.lax.all_gather(
                    concat[:, tb[i]:tb[i + 1]], axis_name, axis=0, tiled=True
                )
                for i in range(len(tb) - 1)
            ],
            axis=1,
        )
    else:
        gathered = jax.lax.all_gather(concat, axis_name, axis=0, tiled=True)
    # [W, sum_m]
    W = gathered.shape[0]
    out, off = [], 0
    for x, k, (moved_shape, m) in zip(leaves, dims, metas):
        full = gathered[:, off:off + m].reshape(
            (W * moved_shape[0],) + tuple(moved_shape[1:])
        )
        out.append(jnp.moveaxis(full, 0, k))
        off += m
    return out


def bucketed_psum_scatter(
    leaves: Sequence[jax.Array],
    dims: Sequence[int],
    axis_name: str,
    mean: bool = True,
) -> List[jax.Array]:
    """Unquantized bucket reduce-scatter: rows laid out [W, shard] per leaf
    (row w destined for rank w), concatenated along columns, ONE tiled
    psum_scatter, then split — elementwise sums are unchanged, so the
    result matches per-leaf ``psum_scatter(..., scatter_dimension=k)``."""
    W = jax.lax.axis_size(axis_name)
    rows_list, metas = [], []
    for g, k in zip(leaves, dims):
        moved = jnp.moveaxis(g, k, 0)
        rows = moved.reshape(W, -1)
        rows_list.append(rows)
        metas.append((moved.shape, rows.shape[1]))
    reduced = jax.lax.psum_scatter(
        jnp.concatenate(rows_list, axis=1), axis_name,
        scatter_dimension=0, tiled=True,
    )  # [1, sum_m] (tiled: W rows scatter W-ways)
    reduced = reduced.reshape(-1)
    out, off = [], 0
    for g, k, (moved_shape, m) in zip(leaves, dims, metas):
        sl = reduced[off:off + m]
        if mean:
            sl = sl / W
        shard = sl.reshape((moved_shape[0] // W,) + tuple(moved_shape[1:]))
        out.append(jnp.moveaxis(shard, 0, k).astype(g.dtype))
        off += m
    return out
