"""ZeRO package: sharding-plan stages (partition.py), config (config.py),
and deferred sharded construction (init_context.py — the zero.Init analogue,
reference runtime/zero/partition_parameters.py:878)."""

from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.init_context import Init, as_deferred_init
from deepspeed_tpu.runtime.zero.partition import (
    ZeroShardingPlan,
    build_zero_plan,
    choose_zero_spec,
    constrain_tree,
)

__all__ = [
    "DeepSpeedZeroConfig",
    "Init",
    "ZeroShardingPlan",
    "as_deferred_init",
    "build_zero_plan",
    "choose_zero_spec",
    "constrain_tree",
]
