"""SPMD pipeline parallelism: microbatch rotation over the ``pipe`` mesh axis.

The TPU-native execution model replacing the reference's host-driven
instruction dispatch (``PipelineEngine._exec_schedule`` runtime/pipe/
engine.py:1354 + ``p2p.send/recv`` runtime/pipe/p2p.py): every stage runs the
same compiled program; activations rotate between neighbor stages with
``jax.lax.ppermute`` (ICI neighbor exchange) inside a ``lax.scan`` whose trip
count is ``n_micro + n_stages - 1`` (fill + steady + drain). Reverse-mode AD
through the scan/ppermute yields the backward pipeline automatically — the
reference's SendGrad/RecvGrad instructions are the transpose XLA derives.

The pipeline body is manual only over ``pipe`` (shard_map axis_names); data/
model/sequence axes stay in GSPMD auto mode, so ZeRO and TP compose unchanged.
"""

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import PIPE_AXIS, Topology, get_topology


def _tree_index(tree, i):
    return jax.tree.map(lambda l: jax.lax.dynamic_index_in_dim(l, i, keepdims=False), tree)


def _tree_update(tree, val, i):
    return jax.tree.map(
        lambda l, v: jax.lax.dynamic_update_index_in_dim(l, v, i, 0), tree, val
    )


def _tree_where(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def pipeline_apply(
    stage_fn: Callable,
    stage_params: Any,
    x_micro: Any,
    *extra_args,
    topo: Topology = None,
) -> Any:
    """Run microbatches through pipeline stages.

    stage_fn(params_one_stage, x, *extra_args) -> y, where x/y are pytrees of
    the SAME structure & shapes (the rotating state — e.g. (activations,
    running_aux_loss)).
    stage_params: pytree, every leaf leading dim = n_stages (sharded on pipe)
    x_micro: pytree with leading [n_micro, ...] on every leaf.
    Returns outputs of the last stage, leading dim [n_micro, ...].
    """
    topo = topo or get_topology()
    S = topo.pipe_parallel_size
    if S <= 1:
        def body(carry, x):
            p = jax.tree.map(lambda l: l[0], stage_params)
            return carry, stage_fn(p, x, *extra_args)

        _, y = jax.lax.scan(body, None, x_micro)
        return y

    leaves = jax.tree_util.tree_leaves(x_micro)
    n_micro = leaves[0].shape[0]
    total = n_micro + S - 1
    perm = [(i, (i + 1) % S) for i in range(S)]

    def per_stage(params, x_micro, *extra):
        # params leaves: [1, ...] (this stage's slice); x_micro leaves: [n_micro, ...]
        params = jax.tree.map(lambda l: l[0], params)
        stage_id = jax.lax.axis_index(PIPE_AXIS)
        is_first = stage_id == 0
        is_last = stage_id == S - 1

        state0 = jax.tree.map(lambda l: jnp.zeros_like(l[0]), x_micro)
        out_buf0 = jax.tree.map(jnp.zeros_like, x_micro)

        def body(carry, i):
            state, out_buf = carry
            x_i = _tree_index(x_micro, jnp.clip(i, 0, n_micro - 1))
            inp = _tree_where(is_first, x_i, state)
            out = stage_fn(params, inp, *extra)
            # last stage emits microbatch i-(S-1) when in range
            mb_out = jnp.clip(i - (S - 1), 0, n_micro - 1)
            emit = jnp.logical_and(is_last, i >= S - 1)
            cur = _tree_index(out_buf, mb_out)
            new = _tree_where(emit, out, cur)
            out_buf = _tree_update(out_buf, new, mb_out)
            state = jax.tree.map(lambda l: jax.lax.ppermute(l, PIPE_AXIS, perm), out)
            return (state, out_buf), None

        (_, out_buf), _ = jax.lax.scan(body, (state0, out_buf0), jnp.arange(total))
        # out_buf is valid only on the last stage; make it uniform across the
        # pipe axis so downstream GSPMD code sees one logical value. psum of
        # the masked buffer = broadcast from last stage.
        out_buf = _tree_where(is_last, out_buf, jax.tree.map(jnp.zeros_like, out_buf))
        return jax.tree.map(lambda l: jax.lax.psum(l, PIPE_AXIS), out_buf)

    in_specs = (
        jax.tree.map(lambda _: P(PIPE_AXIS), stage_params),
        jax.tree.map(lambda _: P(), x_micro),  # replicated over pipe (data/seq stay auto)
    ) + tuple(P() for _ in extra_args)
    fn = jax.shard_map(
        per_stage,
        mesh=topo.mesh,
        in_specs=in_specs,
        out_specs=jax.tree.map(lambda _: P(), x_micro),
        axis_names={PIPE_AXIS},
        check_vma=False,
    )
    return fn(stage_params, x_micro, *extra_args)


def _stack_stages(layer_tree: Any, n_stages: int) -> Any:
    """[L, ...] stacked layer params -> [S, L/S, ...]."""

    def reshape(l):
        L = l.shape[0]
        assert L % n_stages == 0, f"layers {L} not divisible by stages {n_stages}"
        return l.reshape((n_stages, L // n_stages) + l.shape[1:])

    return jax.tree.map(reshape, layer_tree)


def make_pipelined_loss_fn(config, micro_batches: int, topo: Topology = None):
    """Causal-LM loss with the transformer layer stack pipelined over ``pipe``.

    Embedding and the LM head run outside the pipeline (replicated over the
    pipe axis, sharded over data/model as usual) through the same
    ``embed_tokens``/``lm_head_loss`` helpers as the dense path; the layer
    scan is split into contiguous stages (the reference's uniform
    partition_method, runtime/pipe/module.py:393). Honors labels/loss_mask/
    positions/segment_ids batch keys and threads the MoE aux loss through the
    rotating state.
    """
    from deepspeed_tpu.models import transformer as T

    topo = topo or get_topology()
    S = topo.pipe_parallel_size
    c = config

    def stage_fn(stage_layers, state, positions, segment_ids):
        x, aux = state
        layer = functools.partial(T._layer, c)
        if c.remat:
            layer = jax.checkpoint(
                layer, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            )

        def body(carry, lp):
            h, a = carry
            h, a_l = layer(lp, h, positions, segment_ids)
            return (h, a + a_l), None

        (x, aux), _ = jax.lax.scan(body, (x, aux), stage_layers)
        return x, aux

    def loss_fn(params, batch):
        inputs, labels, mask, positions, segment_ids = T.split_lm_batch(batch)
        b, s = inputs.shape
        assert b % micro_batches == 0, f"batch {b} not divisible by micro_batches {micro_batches}"
        if positions is None:
            positions = jnp.arange(s, dtype=jnp.int32)

        x = T.embed_tokens(params, inputs, positions, c)
        mb = b // micro_batches
        x_micro = x.reshape((micro_batches, mb) + x.shape[1:])
        aux_micro = jnp.zeros((micro_batches,), jnp.float32)
        seg_micro = (
            segment_ids.reshape((micro_batches, mb) + segment_ids.shape[1:])
            if segment_ids is not None
            else None
        )
        stage_params = _stack_stages(params["layers"], S)

        if seg_micro is None:
            y_micro, aux_out = pipeline_apply(
                lambda p, st, pos: stage_fn(p, st, pos, None),
                stage_params, (x_micro, aux_micro), positions, topo=topo,
            )
        else:
            # segment ids travel with their microbatch as rotating state
            def stage_seg(p, st, pos):
                (x, aux), seg = st[0], st[1]
                y, a = stage_fn(p, (x, aux), pos, seg)
                return (y, a), seg

            (y_micro, aux_out), _ = pipeline_apply(
                stage_seg, stage_params, ((x_micro, aux_micro), seg_micro), positions, topo=topo,
            )

        y = y_micro.reshape((b,) + y_micro.shape[2:])
        # per-microbatch aux losses are means over that microbatch's tokens;
        # average them so the scale matches the dense (one-gating-call) path
        aux = jnp.sum(aux_out) / micro_batches
        return T.lm_head_loss(params, y, labels, mask, c, aux=aux)

    return loss_fn


def pipeline_partition_specs(config, topo: Topology = None) -> Any:
    """Param PartitionSpecs for the pipelined transformer: layer-stack leading
    dim sharded over ``pipe``, composed with the TP specs."""
    from deepspeed_tpu.models import param_partition_specs

    specs = param_partition_specs(config)

    def add_pipe(spec):
        rest = tuple(spec)[1:] if len(spec) else ()
        return P(PIPE_AXIS, *rest)

    specs["layers"] = jax.tree.map(
        add_pipe, specs["layers"], is_leaf=lambda x: isinstance(x, P)
    )
    return specs
