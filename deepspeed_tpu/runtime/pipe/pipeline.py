"""SPMD pipeline parallelism: microbatch rotation over the ``pipe`` mesh axis.

The TPU-native execution model replacing the reference's host-driven
instruction dispatch (``PipelineEngine._exec_schedule`` runtime/pipe/
engine.py:1354 + ``p2p.send/recv`` runtime/pipe/p2p.py): every stage runs the
same compiled program; activations rotate between neighbor stages with
``jax.lax.ppermute`` (ICI neighbor exchange) inside a ``lax.scan`` whose trip
count is ``n_micro + n_stages - 1`` (fill + steady + drain). Reverse-mode AD
through the scan/ppermute yields the backward pipeline automatically — the
reference's SendGrad/RecvGrad instructions are the transpose XLA derives.

The pipeline body is manual only over ``pipe`` (shard_map axis_names); data/
model/sequence axes stay in GSPMD auto mode, so ZeRO and TP compose unchanged.
"""

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import (
    BATCH_AXES,
    PIPE_AXIS,
    Topology,
    constrain,
    get_topology,
)


def _tree_index(tree, i):
    return jax.tree.map(lambda l: jax.lax.dynamic_index_in_dim(l, i, keepdims=False), tree)


def _tree_update(tree, val, i):
    return jax.tree.map(
        lambda l, v: jax.lax.dynamic_update_index_in_dim(l, v, i, 0), tree, val
    )


def _tree_where(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def pipeline_apply(
    stage_fn: Callable,
    stage_params: Any,
    x_micro: Any,
    *extra_args,
    topo: Topology = None,
    comm_quant: str = "none",
) -> Any:
    """Run microbatches through pipeline stages.

    stage_fn(params_one_stage, x, *extra_args) -> y, where x/y are pytrees of
    the SAME structure & shapes (the rotating state — e.g. (activations,
    running_aux_loss)).
    stage_params: pytree, every leaf leading dim = n_stages (sharded on pipe)
    x_micro: pytree with leading [n_micro, ...] on every leaf.
    comm_quant: "int8" sends the rotating activations between stages as int8
    payloads + fp32 block scales riding the same ppermute
    (comm.quantized.quantized_ppermute); "none" keeps full-width sends.
    Returns outputs of the last stage, leading dim [n_micro, ...].
    """
    from deepspeed_tpu.comm.quantized import check_comm_quant

    comm_quant = check_comm_quant(comm_quant)
    topo = topo or get_topology()
    S = topo.pipe_parallel_size
    if S <= 1:
        def body(carry, x):
            p = jax.tree.map(lambda l: l[0], stage_params)
            return carry, stage_fn(p, x, *extra_args)

        _, y = jax.lax.scan(body, None, x_micro)
        return y

    leaves = jax.tree_util.tree_leaves(x_micro)
    n_micro = leaves[0].shape[0]
    total = n_micro + S - 1
    perm = [(i, (i + 1) % S) for i in range(S)]

    def per_stage(params, x_micro, *extra):
        # params leaves: [1, ...] (this stage's slice); x_micro leaves: [n_micro, ...]
        params = jax.tree.map(lambda l: l[0], params)
        stage_id = jax.lax.axis_index(PIPE_AXIS)
        is_first = stage_id == 0
        is_last = stage_id == S - 1

        state0 = jax.tree.map(lambda l: jnp.zeros_like(l[0]), x_micro)
        out_buf0 = jax.tree.map(jnp.zeros_like, x_micro)

        def body(carry, i):
            state, out_buf = carry
            x_i = _tree_index(x_micro, jnp.clip(i, 0, n_micro - 1))
            inp = _tree_where(is_first, x_i, state)
            out = stage_fn(params, inp, *extra)
            # last stage emits microbatch i-(S-1) when in range
            mb_out = jnp.clip(i - (S - 1), 0, n_micro - 1)
            emit = jnp.logical_and(is_last, i >= S - 1)
            cur = _tree_index(out_buf, mb_out)
            new = _tree_where(emit, out, cur)
            out_buf = _tree_update(out_buf, new, mb_out)
            if comm_quant == "int8":
                from deepspeed_tpu.comm.quantized import quantized_ppermute

                state = quantized_ppermute(out, PIPE_AXIS, perm, tag="pipe_fwd")
            else:
                # intentionally raw: the comm_quant="none" contract is a
                # bit-identical full-width send
                state = jax.tree.map(lambda l: jax.lax.ppermute(l, PIPE_AXIS, perm), out)  # dstpu: noqa[raw-collective-in-hot-path]
            return (state, out_buf), None

        (_, out_buf), _ = jax.lax.scan(body, (state0, out_buf0), jnp.arange(total))
        # out_buf is valid only on the last stage; make it uniform across the
        # pipe axis so downstream GSPMD code sees one logical value. psum of
        # the masked buffer = broadcast from last stage.
        out_buf = _tree_where(is_last, out_buf, jax.tree.map(jnp.zeros_like, out_buf))
        # broadcast-from-last-stage, not a wire-bound reduction — stays raw
        return jax.tree.map(lambda l: jax.lax.psum(l, PIPE_AXIS), out_buf)  # dstpu: noqa[raw-collective-in-hot-path]

    in_specs = (
        jax.tree.map(lambda _: P(PIPE_AXIS), stage_params),
        jax.tree.map(lambda _: P(), x_micro),  # replicated over pipe (data/seq stay auto)
    ) + tuple(P() for _ in extra_args)
    fn = jax.shard_map(
        per_stage,
        mesh=topo.mesh,
        in_specs=in_specs,
        out_specs=jax.tree.map(lambda _: P(), x_micro),
        axis_names={PIPE_AXIS},
        check_vma=False,
    )
    return fn(stage_params, x_micro, *extra_args)


def _stack_stages(layer_tree: Any, n_stages: int) -> Any:
    """[L, ...] stacked layer params -> [S, L/S, ...]."""

    def reshape(l):
        L = l.shape[0]
        if L % n_stages != 0:
            raise ValueError(f"layers {L} not divisible by stages {n_stages}")
        return l.reshape((n_stages, L // n_stages) + l.shape[1:])

    return jax.tree.map(reshape, layer_tree)


def make_pipelined_loss_fn(
    config, micro_batches: int, topo: Topology = None, comm_quant: str = None
):
    """Causal-LM loss with the transformer layer stack pipelined over ``pipe``.

    Embedding and the LM head run outside the pipeline (replicated over the
    pipe axis, sharded over data/model as usual) through the same
    ``embed_tokens``/``lm_head_loss`` helpers as the dense path; the layer
    scan is split into contiguous stages (the reference's uniform
    partition_method, runtime/pipe/module.py:393). Honors labels/loss_mask/
    positions/segment_ids batch keys and threads the MoE aux loss through the
    rotating state.

    comm_quant: "int8" rides the inter-stage activation sends on
    ``comm.quantized.quantized_ppermute``; defaults to the model config's
    ``comm_quant`` field.
    """
    from deepspeed_tpu.comm.quantized import check_comm_quant
    from deepspeed_tpu.models import transformer as T

    topo = topo or get_topology()
    S = topo.pipe_parallel_size
    c = config
    comm_quant = check_comm_quant(
        comm_quant if comm_quant is not None else getattr(c, "comm_quant", "none")
    )

    def stage_fn(stage_layers, state, positions, segment_ids):
        x, aux = state
        layer = functools.partial(T._layer, c)
        if c.remat:
            layer = jax.checkpoint(
                layer, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            )

        def body(carry, lp):
            h, a = carry
            h, a_l = layer(lp, h, positions, segment_ids)
            return (h, a + a_l), None

        (x, aux), _ = jax.lax.scan(body, (x, aux), stage_layers)
        return x, aux

    def loss_fn(params, batch):
        inputs, labels, mask, positions, segment_ids = T.split_lm_batch(batch)
        b, s = inputs.shape
        if b % micro_batches != 0:
            raise ValueError(f"batch {b} not divisible by micro_batches {micro_batches}")
        if positions is None:
            positions = jnp.arange(s, dtype=jnp.int32)

        x = T.embed_tokens(params, inputs, positions, c)
        mb = b // micro_batches
        x_micro = x.reshape((micro_batches, mb) + x.shape[1:])
        # Pre-shard the microbatch stack to the exact layout the pipe
        # shard_map consumes (replicated over pipe, batch over data): without
        # this GSPMD bridges the gap with an involuntary full
        # rematerialization — a whole-tensor replicate per step (VERDICT r2).
        x_micro = constrain(x_micro, None, BATCH_AXES)
        aux_micro = jnp.zeros((micro_batches,), jnp.float32)
        # per-microbatch metadata (packed batches) travels with the rotating
        # state; shared [s] positions ride as a plain broadcast arg
        meta = {}
        if segment_ids is not None:
            meta["seg"] = segment_ids.reshape((micro_batches, mb) + segment_ids.shape[1:])
        if positions.ndim == 2:
            meta["pos"] = positions.reshape((micro_batches, mb) + positions.shape[1:])
            positions_arg = jnp.arange(s, dtype=jnp.int32)  # unused placeholder
        else:
            positions_arg = positions
        stage_params = _stack_stages(params["layers"], S)

        if not meta:
            y_micro, aux_out = pipeline_apply(
                lambda p, st, pos: stage_fn(p, st, pos, None),
                stage_params, (x_micro, aux_micro), positions_arg, topo=topo,
                comm_quant=comm_quant,
            )
        else:

            def stage_meta(p, st, pos):
                (x, aux), md = st
                y, a = stage_fn(p, (x, aux), md.get("pos", pos), md.get("seg"))
                return (y, a), md

            (y_micro, aux_out), _ = pipeline_apply(
                stage_meta, stage_params, ((x_micro, aux_micro), meta), positions_arg, topo=topo,
                comm_quant=comm_quant,
            )

        y = y_micro.reshape((b,) + y_micro.shape[2:])
        # per-microbatch aux losses are means over that microbatch's tokens;
        # average them so the scale matches the dense (one-gating-call) path
        aux = jnp.sum(aux_out) / micro_batches
        return T.lm_head_loss(params, y, labels, mask, c, aux=aux)

    return loss_fn


def _tree_add_where(pred, acc, delta):
    return jax.tree.map(lambda a, d: a + jnp.where(pred, d, jnp.zeros_like(d)), acc, delta)


class Pipelined1F1BLoss:
    """Pipelined causal-LM loss with a TRUE 1F1B executing schedule.

    The GPipe-shaped rotation (``make_pipelined_loss_fn`` + autodiff) keeps
    every microbatch's stage activations alive until the scan's backward —
    O(n_micro) liveness per stage (VERDICT weak #7). This executor reproduces
    the reference ``TrainSchedule`` memory property (runtime/pipe/engine.py:60,
    schedule.py:189): forward and backward INTERLEAVE inside one scan, so a
    stage holds at most ``2*(S-1-stage_id)+1`` in-flight microbatch inputs —
    bounded by the stage count, independent of n_micro.

    Mechanics (all SPMD over the ``pipe`` axis, one compiled program):
      * tick t, stage s: forward of microbatch ``f = t - s`` and backward of
        microbatch ``b = t - (2S-2) + s`` (on the last stage b == f: the
        "1F then 1B" of the same microbatch, reference steady state).
      * backward is hand-driven ``jax.vjp`` per stage per tick; only the
        stage INPUT is saved (circular buffer of depth 2S), the stage body
        recomputes under its remat policy inside the tick.
      * the LM head + loss run inside the region on the last stage the tick
        a microbatch's forward completes (lax.cond — other stages skip the
        compute), producing the output cotangent that starts its backward
        the same tick. The embedding's gather-vjp likewise runs on stage 0
        at each backward tick.
      * activation sends ride ``ppermute`` (i→i+1); cotangent sends ride the
        reverse permutation — the SendGrad/RecvGrad instructions, fused into
        the same tick.

    Loss is the mean of per-microbatch means (the reference's
    ``_aggregate_total_loss`` semantics); with non-uniform loss masks this
    differs from the dense path's global-mask normalization.

    Tied embeddings (gpt2/gemma-style): the embedding table joins the head's
    vjp inputs on the last stage, and its two grad contributions — stage-0
    embedding-gather vjp and last-stage head-matmul vjp — are summed after
    their psums. That IS the reference's tied-weight reduce
    (``ReduceTiedGrads``, runtime/pipe/engine.py:274 + the TiedLayerSpec
    group all-reduce, pipe/module.py:77), collapsed to one add because this
    SPMD formulation replicates embed/head params over the pipe axis rather
    than owning them on single ranks.

    Restrictions: fp16 loss-scaling unsupported (the engine applies scaling
    around autodiff, not custom grads).
    """

    def __init__(
        self, config, micro_batches: int, topo: Topology = None, comm_quant: str = None
    ):
        from deepspeed_tpu.comm.quantized import check_comm_quant
        from deepspeed_tpu.parallel.topology import MODEL_AXIS

        self.config = config
        self.micro_batches = micro_batches
        self.topo = topo or get_topology()
        self.comm_quant = check_comm_quant(
            comm_quant
            if comm_quant is not None
            else getattr(config, "comm_quant", "none")
        )
        if (
            config.tie_embeddings
            and config.vocab_parallel
            and self.topo.axis_size(MODEL_AXIS) > 1
            and self.topo.pipe_parallel_size > 1
        ):
            raise ValueError(
                "1F1B with tied embeddings does not support vocab_parallel=True "
                "on a model axis > 1: the tied head's embed-table vjp runs inside "
                "the pipe shard_map manual region, where a model-sharded vocab dim "
                "trips an XLA spmd_partitioner group-assignment CHECK-crash — set "
                "vocab_parallel=False on the model config (replicated embeddings)"
            )
        self._fwd_loss = make_pipelined_loss_fn(
            config, micro_batches, self.topo, comm_quant=self.comm_quant
        )

    def __call__(self, params, batch):
        return self._fwd_loss(params, batch)

    def custom_value_and_grad(self, params, batch):
        """(loss, grads) with 1F1B liveness. Engine hook: when a loss_fn
        exposes ``custom_value_and_grad``, the train step uses it instead of
        ``jax.value_and_grad``."""
        from deepspeed_tpu.models import transformer as T

        c = self.config
        topo = self.topo
        comm_quant = self.comm_quant
        S = topo.pipe_parallel_size
        n_micro = self.micro_batches
        if S <= 1:
            return jax.value_and_grad(self._fwd_loss)(params, batch)

        inputs, labels, mask, positions, segment_ids = T.split_lm_batch(batch)
        b, s = inputs.shape
        if b % n_micro != 0:
            raise ValueError(f"batch {b} not divisible by micro_batches {n_micro}")
        mb = b // n_micro
        if positions is None:
            positions = jnp.arange(s, dtype=jnp.int32)
        if mask is None:
            mask = jnp.ones(labels.shape, jnp.float32)
        has_seg = segment_ids is not None

        tokens_m = inputs.reshape(n_micro, mb, s)
        labels_m = labels.reshape(n_micro, mb, s)
        mask_m = mask.reshape(n_micro, mb, s)
        seg_m = segment_ids.reshape(n_micro, mb, s) if has_seg else jnp.zeros((n_micro, 1, 1), jnp.int32)

        stage_params = _stack_stages(params["layers"], S)
        head_keys = [
            k for k in ("final_norm", "final_norm_b", "lm_head", "lm_head_b") if k in params
        ]
        if c.tie_embeddings:
            # tied head reads params["embed"]: the table must be a head-vjp
            # input so the last stage produces its head-matmul gradient
            head_keys.append("embed")
        embed_keys = [
            k for k in ("embed", "pos_embed", "embed_norm", "embed_norm_b") if k in params
        ]
        head_params = {k: params[k] for k in head_keys}
        embed_params = {k: params[k] for k in embed_keys}

        D = 2 * S  # circular save-buffer depth: covers max in-flight 2(S-1)+1
        total = n_micro + 2 * S - 2
        perm_f = [(i, (i + 1) % S) for i in range(S)]
        perm_b = [((i + 1) % S, i) for i in range(S)]

        # per-example positions ([b, s], packed batches) split per microbatch
        # exactly like segment_ids; shared [s] positions broadcast as-is
        per_ex_pos = positions.ndim == 2
        pos_m = positions.reshape(n_micro, mb, s) if per_ex_pos else positions

        def mb_positions(i):
            return pos_m[i] if per_ex_pos else pos_m

        def run_stage(sp, state, seg, pos):
            layer = functools.partial(T._layer, c)
            if c.remat:
                layer = jax.checkpoint(
                    layer, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                )

            def body(carry, lp):
                h, a = carry
                h, a_l = layer(lp, h, pos, seg if has_seg else None)
                return (h, a + a_l), None

            out, _ = jax.lax.scan(body, state, sp)
            return out

        def head_loss(hp, y, aux, i):
            # closes over labels_m/mask_m (replicated over pipe): only head
            # PARAMS need to be vjp inputs
            full = dict(hp)
            return T.lm_head_loss(full, y, labels_m[i], mask_m[i], c, aux=aux)

        def per_stage(stage_params, tokens_m, seg_m, head_params, embed_params):
            sp = jax.tree.map(lambda l: l[0], stage_params)  # this stage's [L/S, ...]
            sid = jax.lax.axis_index(PIPE_AXIS)
            is_first = sid == 0
            is_last = sid == S - 1

            x_tmpl = jnp.zeros((mb, s, c.hidden_size), T.DTYPES[c.dtype])
            state_tmpl = (x_tmpl, jnp.float32(0.0))
            zeros_hg = jax.tree.map(jnp.zeros_like, head_params)

            def embed_mb(i):
                return T.embed_tokens(embed_params, tokens_m[i], mb_positions(i), c)

            carry0 = (
                state_tmpl,  # fwd_in
                state_tmpl,  # bwd_in (cotangents share the state structure)
                jax.tree.map(lambda l: jnp.zeros((D,) + l.shape, l.dtype), state_tmpl),  # xsave
                jax.tree.map(jnp.zeros_like, sp),  # layer grads
                jax.tree.map(jnp.zeros_like, embed_params),  # embed grads
                zeros_hg,  # head grads
                jnp.float32(0.0),  # loss
            )

            def tick(carry, t):
                fwd_in, bwd_in, xsave, lg, eg, hg, loss_acc = carry
                f = t - sid
                f_valid = (f >= 0) & (f < n_micro)
                bi = t - (2 * S - 2) + sid
                b_valid = (bi >= 0) & (bi < n_micro)
                fidx = jnp.clip(f, 0, n_micro - 1)
                bidx = jnp.clip(bi, 0, n_micro - 1)
                seg_f = seg_m[fidx] if has_seg else None
                seg_b = seg_m[bidx] if has_seg else None
                pos_f = mb_positions(fidx)
                pos_b = mb_positions(bidx)

                # ---- forward of microbatch f
                x_first = jax.lax.cond(
                    is_first, lambda: embed_mb(fidx), lambda: jnp.zeros_like(x_tmpl)
                )
                x_in = (
                    jnp.where(is_first, x_first, fwd_in[0]),
                    jnp.where(is_first, 0.0, fwd_in[1]),
                )
                y_state = run_stage(sp, x_in, seg_f, pos_f)

                # save the stage input for this microbatch's backward
                slot = fidx % D
                xsave = jax.tree.map(
                    lambda buf, v: jax.lax.dynamic_update_index_in_dim(
                        buf,
                        jnp.where(f_valid, v, jax.lax.dynamic_index_in_dim(buf, slot, keepdims=False)),
                        slot,
                        0,
                    ),
                    xsave,
                    x_in,
                )

                # ---- head + loss on the last stage (same tick starts backward)
                def do_head():
                    lo, vjp = jax.vjp(
                        lambda hp, yy, aa: head_loss(hp, yy, aa, fidx), head_params, *y_state
                    )
                    dhp, dy, daux = vjp(jnp.float32(1.0))
                    return lo, dhp, dy, daux

                def no_head():
                    return jnp.float32(0.0), zeros_hg, jnp.zeros_like(x_tmpl), jnp.float32(0.0)

                head_on = is_last & f_valid
                # gate on validity too: fill/drain ticks skip the full-vocab
                # head matmul + vjp instead of computing-then-zeroing it
                loss_f, dhp, dy_head, daux_head = jax.lax.cond(head_on, do_head, no_head)
                loss_acc = loss_acc + jnp.where(head_on, loss_f / n_micro, 0.0)
                hg = _tree_add_where(head_on, hg, jax.tree.map(lambda g: g / n_micro, dhp))

                # ---- backward of microbatch b
                x_in_b = jax.tree.map(
                    lambda buf: jax.lax.dynamic_index_in_dim(buf, bidx % D, keepdims=False), xsave
                )
                dy_b = (
                    jnp.where(is_last, dy_head / n_micro, bwd_in[0]),
                    jnp.where(is_last, daux_head / n_micro, bwd_in[1]),
                )
                _, vjp_stage = jax.vjp(lambda p, st: run_stage(p, st, seg_b, pos_b), sp, x_in_b)
                dp, dstate = vjp_stage(dy_b)
                lg = _tree_add_where(b_valid, lg, dp)

                def do_embed_grad():
                    _, evjp = jax.vjp(lambda ep: T.embed_tokens(ep, tokens_m[bidx], pos_b, c), embed_params)
                    (dep,) = evjp(dstate[0])
                    return dep

                def no_embed_grad():
                    return jax.tree.map(jnp.zeros_like, embed_params)

                embed_on = b_valid & is_first
                dep = jax.lax.cond(embed_on, do_embed_grad, no_embed_grad)
                eg = _tree_add_where(embed_on, eg, dep)

                # ---- neighbor exchange: activations forward, cotangents back
                if comm_quant == "int8":
                    from deepspeed_tpu.comm.quantized import quantized_ppermute

                    fwd_out = quantized_ppermute(y_state, PIPE_AXIS, perm_f, tag="pipe_fwd")
                    bwd_out = quantized_ppermute(dstate, PIPE_AXIS, perm_b, tag="pipe_bwd")
                else:
                    # intentionally raw: comm_quant="none" promises a
                    # bit-identical full-width exchange
                    fwd_out = jax.tree.map(lambda l: jax.lax.ppermute(l, PIPE_AXIS, perm_f), y_state)  # dstpu: noqa[raw-collective-in-hot-path]
                    bwd_out = jax.tree.map(lambda l: jax.lax.ppermute(l, PIPE_AXIS, perm_b), dstate)  # dstpu: noqa[raw-collective-in-hot-path]
                return (fwd_out, bwd_out, xsave, lg, eg, hg, loss_acc), None

            (fwd_in, bwd_in, xsave, lg, eg, hg, loss_acc), _ = jax.lax.scan(
                tick, carry0, jnp.arange(total)
            )
            # contributions live on single stages → psum replicates them
            # (once-per-step broadcasts, not wire-bound — stay raw)
            loss_out = jax.lax.psum(loss_acc, PIPE_AXIS)  # dstpu: noqa[raw-collective-in-hot-path]
            eg = jax.tree.map(lambda l: jax.lax.psum(l, PIPE_AXIS), eg)  # dstpu: noqa[raw-collective-in-hot-path]
            hg = jax.tree.map(lambda l: jax.lax.psum(l, PIPE_AXIS), hg)  # dstpu: noqa[raw-collective-in-hot-path]
            lg = jax.tree.map(lambda l: l[None], lg)  # re-grow the pipe dim
            return loss_out, lg, eg, hg

        in_specs = (
            jax.tree.map(lambda _: P(PIPE_AXIS), stage_params),
            P(), P(),
            jax.tree.map(lambda _: P(), head_params),
            jax.tree.map(lambda _: P(), embed_params),
        )
        out_specs = (
            P(),
            jax.tree.map(lambda _: P(PIPE_AXIS), stage_params),
            jax.tree.map(lambda _: P(), embed_params),
            jax.tree.map(lambda _: P(), head_params),
        )
        fn = jax.shard_map(
            per_stage,
            mesh=topo.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            axis_names={PIPE_AXIS},
            check_vma=False,
        )
        loss, lg, eg, hg = fn(stage_params, tokens_m, seg_m, head_params, embed_params)

        L = jax.tree_util.tree_leaves(params["layers"])[0].shape[0]
        grads = dict(eg)
        for k, g in hg.items():
            # tied embeddings: "embed" appears in BOTH eg (stage-0 gather vjp)
            # and hg (last-stage head vjp) — their sum is the tied-grad reduce
            grads[k] = grads[k] + g if k in grads else g
        grads["layers"] = jax.tree.map(lambda l: l.reshape((L,) + l.shape[2:]), lg)
        return loss, grads


def make_1f1b_loss_fn(
    config, micro_batches: int, topo: Topology = None, comm_quant: str = None
) -> Pipelined1F1BLoss:
    """The 1F1B pipelined loss (see :class:`Pipelined1F1BLoss`)."""
    return Pipelined1F1BLoss(config, micro_batches, topo, comm_quant=comm_quant)


def pipeline_partition_specs(config, topo: Topology = None) -> Any:
    """Param PartitionSpecs for the pipelined transformer: layer-stack leading
    dim sharded over ``pipe``, composed with the TP specs."""
    from deepspeed_tpu.models import param_partition_specs

    specs = param_partition_specs(config)

    def add_pipe(spec):
        rest = tuple(spec)[1:] if len(spec) else ()
        return P(PIPE_AXIS, *rest)

    specs["layers"] = jax.tree.map(
        add_pipe, specs["layers"], is_leaf=lambda x: isinstance(x, P)
    )
    return specs
