"""Pipeline parallelism (reference runtime/pipe/ + deepspeed/pipe/)."""

from deepspeed_tpu.runtime.pipe.module import (
    LayerSpec,
    PipelineModule,
    TiedLayerSpec,
    partition_balanced,
    partition_uniform,
)
from deepspeed_tpu.runtime.pipe.pipeline import (
    make_pipelined_loss_fn,
    pipeline_apply,
    pipeline_partition_specs,
)
from deepspeed_tpu.runtime.pipe.schedule import (
    BackwardPass,
    DataParallelSchedule,
    ForwardPass,
    InferenceSchedule,
    LoadMicroBatch,
    OptimizerStep,
    PipeInstruction,
    PipeSchedule,
    RecvActivation,
    RecvGrad,
    ReduceGrads,
    ReduceTiedGrads,
    SendActivation,
    SendGrad,
    TrainSchedule,
)

__all__ = [
    "LayerSpec",
    "PipelineModule",
    "TiedLayerSpec",
    "partition_balanced",
    "partition_uniform",
    "make_pipelined_loss_fn",
    "pipeline_apply",
    "pipeline_partition_specs",
    "PipeSchedule",
    "TrainSchedule",
    "InferenceSchedule",
    "DataParallelSchedule",
    "PipeInstruction",
    "ForwardPass",
    "BackwardPass",
    "SendActivation",
    "RecvActivation",
    "SendGrad",
    "RecvGrad",
    "LoadMicroBatch",
    "ReduceGrads",
    "ReduceTiedGrads",
    "OptimizerStep",
]
