"""PipelineModule: user-facing stage composition & partitioning.

Reference: ``PipelineModule``/``LayerSpec``/``TiedLayerSpec``
(runtime/pipe/module.py:86,30,77) and the layer partitioner
``_partition_layers`` (:393) with methods uniform / parameters / type:regex.

TPU adaptation: layers are (init_fn, apply_fn) pairs over param pytrees
rather than nn.Modules. Two execution modes:
  * ``forward`` — host-sequential apply (any layer mix), used for numerics
    references and single-stage runs;
  * ``to_pipeline()`` — for a homogeneous layer stack (identical param
    structure + one shared apply_fn), returns ``(stage_fn, stage_params)``
    for the SPMD executor ``runtime/pipe/pipeline.pipeline_apply``.
Tied layers share one param entry (the reference's tied-weight broadcast/
allreduce becomes plain GSPMD replication — every stage reads the same array
and the gradient psum falls out of AD).
"""

import re

import jax.numpy as jnp
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np


class LayerSpec:
    """Deferred layer: built lazily at partition time (reference module.py:30)."""

    def __init__(self, typename: Callable, *args, **kwargs):
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.typename(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """Layer sharing params with all other layers of the same key
    (reference module.py:77)."""

    def __init__(self, key: str, typename: Callable, *args, forward_fn=None, **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Balanced contiguous split bounds (len num_parts+1)."""
    bounds = [0]
    for p in range(1, num_parts + 1):
        bounds.append(round(p * num_items / num_parts))
    return bounds


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Contiguous partition minimizing the max part weight (the reference's
    ds_utils.partition_balanced used for method='parameters'): binary search
    on the bottleneck + greedy packing."""
    weights = list(weights)
    n = len(weights)
    if num_parts >= n:
        return list(range(n + 1)) + [n] * (num_parts - n)
    lo = max(weights)
    hi = sum(weights)

    def feasible(cap):
        parts, acc = 1, 0.0
        for w in weights:
            if acc + w > cap:
                parts += 1
                acc = w
                if parts > num_parts:
                    return False
            else:
                acc += w
        return True

    for _ in range(60):
        mid = (lo + hi) / 2
        if feasible(mid):
            hi = mid
        else:
            lo = mid
    cap = hi
    bounds = [0]
    acc = 0.0
    for i, w in enumerate(weights):
        if acc + w > cap and len(bounds) < num_parts:
            bounds.append(i)
            acc = w
        else:
            acc += w
    bounds.append(n)
    while len(bounds) < num_parts + 1:
        bounds.insert(-1, bounds[-2])
    return bounds


class PipelineModule:
    """Compose layers into pipeline stages.

    layers: list of LayerSpec / (init_fn, apply_fn) / callables.
    Built layers are (params_pytree, apply_fn(params, x) -> x) pairs.
    """

    def __init__(
        self,
        layers: Sequence,
        num_stages: Optional[int] = None,
        topology=None,
        loss_fn: Optional[Callable] = None,
        partition_method: str = "parameters",
        seed: int = 0,
    ):
        from deepspeed_tpu.parallel.topology import get_topology

        self.topo = topology or get_topology()
        self.num_stages = num_stages or self.topo.pipe_parallel_size
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self._specs = list(layers)
        self._key = jax.random.key(seed)
        self.tied_params: Dict[str, Any] = {}
        self._build()
        self._partition()

    def _build(self):
        self.layer_params: List[Any] = []
        self.layer_fns: List[Callable] = []
        self.layer_names: List[str] = []
        keys = jax.random.split(self._key, max(len(self._specs), 1))
        for i, spec in enumerate(self._specs):
            if isinstance(spec, TiedLayerSpec):
                built = spec.build()
                params, fn = self._as_layer(built, keys[i])
                if spec.key not in self.tied_params:
                    self.tied_params[spec.key] = params
                self.layer_params.append({"__tied__": spec.key})
                self.layer_fns.append(spec.forward_fn or fn)
                self.layer_names.append(f"tied:{spec.key}")
            elif isinstance(spec, LayerSpec):
                built = spec.build()
                params, fn = self._as_layer(built, keys[i])
                self.layer_params.append(params)
                self.layer_fns.append(fn)
                self.layer_names.append(getattr(spec.typename, "__name__", str(i)))
            else:
                params, fn = self._as_layer(spec, keys[i])
                self.layer_params.append(params)
                self.layer_fns.append(fn)
                self.layer_names.append(getattr(spec, "__name__", str(i)))

    @staticmethod
    def _as_layer(obj, key):
        """Normalize a layer object to (params, apply_fn)."""
        if isinstance(obj, tuple) and len(obj) == 2 and callable(obj[0]) and callable(obj[1]):
            init_fn, apply_fn = obj
            return init_fn(key), apply_fn
        if hasattr(obj, "init") and hasattr(obj, "apply"):
            return obj.init(key), obj.apply
        if callable(obj):  # parameterless layer (e.g. activation)
            return {}, (lambda params, x, _f=obj: _f(x))
        raise TypeError(f"Cannot interpret pipeline layer {obj!r}")

    def _layer_weights(self):
        out = []
        for p in self.layer_params:
            if isinstance(p, dict) and "__tied__" in p:
                p = self.tied_params[p["__tied__"]]
            out.append(sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(p)) or 1)
        return out

    def _partition(self):
        n = len(self.layer_fns)
        method = self.partition_method.lower()
        if method == "uniform":
            self.parts = partition_uniform(n, self.num_stages)
        elif method == "parameters":
            self.parts = partition_balanced(self._layer_weights(), self.num_stages)
        elif method.startswith("type:"):
            pat = method.split(":", 1)[1]
            w = [1 if re.search(pat, nm, re.IGNORECASE) else 0 for nm in self.layer_names]
            if sum(w) == 0:
                w = [1] * n
            self.parts = partition_balanced([x or 1e-9 for x in w], self.num_stages)
        else:
            raise ValueError(f"unknown partition_method {self.partition_method}")

    def stage_layers(self, stage_id: int):
        lo, hi = self.parts[stage_id], self.parts[stage_id + 1]
        return list(range(lo, hi))

    def stage_of_layer(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        return self.num_stages - 1

    def params(self):
        """Full params pytree: per-layer list + tied table."""
        return {"layers": self.layer_params, "tied": self.tied_params}

    def forward(self, params, x):
        """Sequential (un-pipelined) forward — the reference ``PipelineModule``
        is also runnable as a plain module; used for numerics tests and
        single-stage runs."""
        for p, fn in zip(params["layers"], self.layer_fns):
            if isinstance(p, dict) and "__tied__" in p:
                p = params["tied"][p["__tied__"]]
            x = fn(p, x)
        return x

    __call__ = forward

    def to_pipeline(self):
        """Stack a homogeneous layer list for the SPMD executor.

        Requires every layer to share one apply_fn and identical param
        structure (the transformer case), and len(layers) % num_stages == 0.
        Returns (stage_fn, stage_params) for ``pipeline_apply``:
        stage_params leaves are [num_stages, layers_per_stage, ...].
        """
        n = len(self.layer_fns)
        if n == 0 or n % self.num_stages != 0:
            raise ValueError(f"{n} layers not divisible by {self.num_stages} stages")
        fn0 = self.layer_fns[0]
        if any(f is not fn0 for f in self.layer_fns) or self.tied_params:
            raise ValueError(
                "to_pipeline() requires a homogeneous untied layer stack; "
                "heterogeneous/tied modules run via forward() or the "
                "transformer path (make_pipelined_loss_fn)"
            )
        treedef0 = jax.tree_util.tree_structure(self.layer_params[0])
        if any(jax.tree_util.tree_structure(p) != treedef0 for p in self.layer_params):
            raise ValueError("layer param structures differ; cannot stack")
        stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *self.layer_params)
        lps = n // self.num_stages
        stage_params = jax.tree.map(
            lambda l: l.reshape((self.num_stages, lps) + l.shape[1:]), stacked
        )

        def stage_fn(params, x, *extra):
            def body(h, lp):
                return fn0(lp, h, *extra), None

            y, _ = jax.lax.scan(body, x, params)
            return y

        return stage_fn, stage_params
