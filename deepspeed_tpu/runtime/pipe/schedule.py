"""Pipeline schedules: instruction streams for pipelined execution.

Reference: ``runtime/pipe/schedule.py`` — ``PipeSchedule`` base (:189
``TrainSchedule``, :135 ``InferenceSchedule``, :301 ``DataParallelSchedule``)
yielding per-step ``PipeInstruction`` lists that ``PipelineEngine
._exec_schedule`` (engine.py:1354) dispatches.

On TPU the executing path is the SPMD rotation pipeline
(runtime/pipe/pipeline.py) — one compiled program, no host instruction
dispatch. The instruction stream remains first-class for:
  * schedule correctness tests (1F1B ordering/liveness invariants),
  * a future host-driven multi-slice executor over DCN,
  * parity with the reference API (custom ``PipeSchedule`` subclasses).
"""

from typing import Iterator, List


class PipeInstruction:
    """Base instruction (reference schedule.py PipeInstruction)."""

    def __init__(self, **kwargs):
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{type(self).__name__}({args})"

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    def __init__(self, buffer_id):
        super().__init__(buffer_id=buffer_id)


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id):
        super().__init__(buffer_id=buffer_id)


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


class PipeSchedule:
    """Schedule over micro_batches for one (stage_id of stages) rank."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        if not 0 <= stage_id < stages:
            raise ValueError(f"stage_id {stage_id} out of range for {stages} stages")
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    def __iter__(self):
        return self.steps()

    def num_pipe_buffers(self) -> int:
        return self.micro_batches


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-drain (reference schedule.py:135)."""

    def steps(self):
        total = self.micro_batches + self.stages - 1
        for step_id in range(total):
            mb = step_id - self.stage_id
            cmds: List[PipeInstruction] = []
            if 0 <= mb < self.micro_batches:
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(mb))
                else:
                    cmds.append(RecvActivation(mb))
                cmds.append(ForwardPass(mb))
                if not self.is_last_stage:
                    cmds.append(SendActivation(mb))
            yield cmds

    def num_pipe_buffers(self):
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B (reference schedule.py:189): warmup forwards, steady-state
    alternating fwd/bwd, cooldown backwards, then grad reduce + step.

    In-flight microbatches per stage never exceed ``stages - stage_id``,
    bounding activation liveness — the property the reference schedule's
    even/odd step arithmetic encodes."""

    def steps(self):
        M, S, s = self.micro_batches, self.stages, self.stage_id
        warmup = min(M, S - s - 1)
        fwd = 0
        bwd = 0

        def fwd_cmds(mb):
            cmds = []
            if self.is_first_stage:
                cmds.append(LoadMicroBatch(mb))
            else:
                cmds.append(RecvActivation(mb))
            cmds.append(ForwardPass(mb))
            if not self.is_last_stage:
                cmds.append(SendActivation(mb))
            return cmds

        def bwd_cmds(mb):
            cmds = []
            if not self.is_last_stage:
                cmds.append(RecvGrad(mb))
            cmds.append(BackwardPass(mb))
            if not self.is_first_stage:
                cmds.append(SendGrad(mb))
            return cmds

        # warmup forwards
        for _ in range(warmup):
            yield fwd_cmds(fwd)
            fwd += 1
        # steady state: 1F1B
        while fwd < M:
            yield fwd_cmds(fwd)
            fwd += 1
            yield bwd_cmds(bwd)
            bwd += 1
        # cooldown backwards
        while bwd < M:
            yield bwd_cmds(bwd)
            bwd += 1
        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]

    def num_pipe_buffers(self):
        return max(2, min(self.micro_batches, self.stages - self.stage_id))


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule (reference schedule.py:301)."""

    def steps(self):
        for mb in range(self.micro_batches):
            yield [LoadMicroBatch(mb), ForwardPass(mb), BackwardPass(mb)]
        yield [ReduceGrads(), OptimizerStep()]

    def num_pipe_buffers(self):
        return 1
