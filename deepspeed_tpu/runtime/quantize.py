"""MoQ — Mixture-of-Quantization (reference ``runtime/quantize.py``):
training-time quantization whose precision ramps down on a schedule,
optionally modulated per layer by Hessian eigenvalues (high-curvature layers
quantize later)."""

from typing import Any, Dict, Optional, Sequence

import jax

from deepspeed_tpu.compression.transforms import fake_quantize
from deepspeed_tpu.runtime.eigenvalue import quantize_period_scale


class Quantizer:
    """Reference Quantizer: start_bits → target_bits halving every
    ``quantize_period`` steps; ``eigenvalues`` (per layer index) stretch each
    layer's period by its normalized curvature."""

    def __init__(
        self,
        q_start_bits: int = 16,
        q_target_bits: int = 8,
        q_period: int = 100,
        q_offset: int = 0,
        use_quantizer_kernel: bool = False,
        eigenvalues: Optional[Dict[int, float]] = None,
    ):
        self.q_start_bits = q_start_bits
        self.q_target_bits = q_target_bits
        self.q_period = q_period
        self.q_offset = q_offset
        self.eigenvalues = eigenvalues
        self._scales = quantize_period_scale(eigenvalues) if eigenvalues else None

    def bits_for(self, step: int, layer: Optional[int] = None) -> int:
        if step < self.q_offset:
            return self.q_start_bits
        period = self.q_period
        if self._scales is not None and layer is not None:
            period = int(self.q_period * (1.0 + self._scales.get(layer, 0.0)))
        halvings = (step - self.q_offset) // max(period, 1)
        bits = self.q_start_bits
        for _ in range(halvings):
            if bits <= self.q_target_bits:
                break
            bits = max(bits // 2, self.q_target_bits)
        return bits

    def quantize(self, params: Any, step: int, layers_key: str = "layers") -> Any:
        """Fake-quantize params at the step's precision; stacked layer leaves
        get per-layer bits when eigenvalues were provided."""
        out = dict(params) if isinstance(params, dict) else params
        if isinstance(params, dict) and layers_key in params and self._scales is not None:
            L = jax.tree_util.tree_leaves(params[layers_key])[0].shape[0]
            import jax.numpy as jnp

            def per_layer(leaf):
                rows = [
                    fake_quantize(leaf[i], self.bits_for(step, i)) for i in range(L)
                ]
                return jnp.stack(rows)

            out[layers_key] = jax.tree.map(per_layer, params[layers_key])
            rest = {k: v for k, v in params.items() if k != layers_key}
            bits = self.bits_for(step)
            for k, v in rest.items():
                out[k] = jax.tree.map(
                    lambda w: fake_quantize(w, bits) if getattr(w, "ndim", 0) >= 2 else w, v
                )
            return out
        bits = self.bits_for(step)
        return jax.tree.map(
            lambda w: fake_quantize(w, bits) if getattr(w, "ndim", 0) >= 2 else w, params
        )
