"""MoQ — Mixture-of-Quantization (reference ``runtime/quantize.py``):
training-time quantization whose precision ramps down on a schedule,
optionally modulated per layer by Hessian eigenvalues (high-curvature layers
quantize later)."""

from typing import Any, Dict, Optional, Sequence

import jax

from deepspeed_tpu.compression.transforms import fake_quantize
from deepspeed_tpu.runtime.eigenvalue import quantize_period_scale


class Quantizer:
    """Reference Quantizer: start_bits → target_bits halving every
    ``quantize_period`` steps; ``eigenvalues`` (per layer index) stretch each
    layer's period by its normalized curvature."""

    def __init__(
        self,
        q_start_bits: int = 16,
        q_target_bits: int = 8,
        q_period: int = 100,
        q_offset: int = 0,
        use_quantizer_kernel: bool = False,
        eigenvalues: Optional[Dict[int, float]] = None,
    ):
        self.q_start_bits = q_start_bits
        self.q_target_bits = q_target_bits
        self.q_period = q_period
        self.q_offset = q_offset
        self.eigenvalues = eigenvalues
        self._scales = quantize_period_scale(eigenvalues) if eigenvalues else None

    def bits_for(self, step: int, layer: Optional[int] = None) -> int:
        if step < self.q_offset:
            return self.q_start_bits
        period = self.q_period
        if self._scales is not None and layer is not None:
            period = int(self.q_period * (1.0 + self._scales.get(layer, 0.0)))
        halvings = (step - self.q_offset) // max(period, 1)
        bits = self.q_start_bits
        for _ in range(halvings):
            if bits <= self.q_target_bits:
                break
            bits = max(bits // 2, self.q_target_bits)
        return bits

    def quantize(self, params: Any, step: int, layers_key: str = "layers") -> Any:
        """Fake-quantize the matmul WEIGHTS at the step's precision (norm
        scales/biases/embeddings are excluded by name, like the compression
        transforms); stacked layer leaves get per-layer bits when eigenvalues
        were provided."""
        from deepspeed_tpu.compression.transforms import _is_weight_leaf
        from deepspeed_tpu.utils.pytree import path_str

        def visit_with(bits_of):
            def visit(path, w):
                if not _is_weight_leaf(path_str(path), w):
                    return w
                return bits_of(path, w)

            return visit

        if isinstance(params, dict) and layers_key in params and self._scales is not None:
            import jax.numpy as jnp

            L = jax.tree_util.tree_leaves(params[layers_key])[0].shape[0]
            out = dict(params)

            def per_layer(path, leaf):
                rows = [fake_quantize(leaf[i], self.bits_for(step, i)) for i in range(L)]
                return jnp.stack(rows)

            out[layers_key] = jax.tree_util.tree_map_with_path(
                visit_with(per_layer), params[layers_key]
            )
            bits = self.bits_for(step)
            for k, v in params.items():
                if k != layers_key:
                    out[k] = jax.tree_util.tree_map_with_path(
                        visit_with(lambda p, w: fake_quantize(w, bits)), v
                    )
            return out
        bits = self.bits_for(step)
        return jax.tree_util.tree_map_with_path(
            visit_with(lambda p, w: fake_quantize(w, bits)), params
        )
