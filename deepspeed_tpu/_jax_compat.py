"""Compatibility shims for older jax releases.

The codebase targets the current jax API surface (``jax.shard_map``,
``jax.typeof``, ``jax.memory.Space``, per-device ``pinned_host`` memories).
Older runtimes (jax < 0.5) ship the same functionality under
``jax.experimental.shard_map`` and have no typed memory spaces on CPU.  This
module patches the gaps once, at package import, so the rest of the code can
use the modern spellings unconditionally:

  * ``jax.shard_map`` — a keyword-normalizing wrapper installed on EVERY jax
    generation: call sites may spell the replication-check flag either
    ``check_rep`` (jax <= 0.4.x) or ``check_vma`` (current jax) and it is
    translated to whichever the underlying API takes — no version sniffing
    at call sites. On old jax the wrapper fronts
    ``jax.experimental.shard_map`` (also dropping ``axis_names``, implicit
    in the mesh there); on new jax it fronts the native ``jax.shard_map``.
  * ``jax.memory.Space`` / ``jax.typeof`` — sentinel fallback.  On a backend
    with a single memory space (CPU without ``pinned_host``) every array
    reports ``Space.Device`` and ``device_put`` to a Space is the identity,
    which preserves numerics: host staging becomes a no-op rather than an
    error.  On real TPU runtimes the native API is untouched.
  * ``host_memory_kind()`` — returns ``"pinned_host"`` when the default
    device exposes that memory kind, else ``None`` (NamedSharding treats
    ``memory_kind=None`` as the default memory).
"""

import types

import jax

__all__ = ["host_memory_kind"]


def _ensure_shard_map():
    native = getattr(jax, "shard_map", None)
    if getattr(native, "_dstpu_compat", False):
        return  # already normalized (module re-import)

    if native is not None:
        # Current jax: the native API takes check_vma. Accept the legacy
        # check_rep spelling too, so wrappers written against either
        # generation run unmodified.
        def shard_map(f, *args, check_rep=None, check_vma=None, **kwargs):
            if check_vma is None:
                check_vma = check_rep
            if check_vma is not None:
                kwargs["check_vma"] = check_vma
            return native(f, *args, **kwargs)
    else:
        # jax <= 0.4.x: front jax.experimental.shard_map, which takes
        # check_rep and no axis_names (implicit in the mesh).
        from jax.experimental.shard_map import shard_map as _legacy_shard_map

        def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      axis_names=None, check_vma=None, check_rep=None,
                      **kwargs):
            del axis_names  # implicit in `mesh` for the legacy API
            if check_rep is None:
                check_rep = check_vma
            if check_rep is not None:
                kwargs["check_rep"] = check_rep
            return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                     out_specs=out_specs, **kwargs)

    shard_map._dstpu_compat = True
    jax.shard_map = shard_map


def _ensure_axis_size():
    if hasattr(jax.lax, "axis_size"):
        return
    from jax._src import core as _core

    def axis_size(axis_name):
        frame = _core.axis_frame(axis_name)
        return getattr(frame, "size", frame)

    jax.lax.axis_size = axis_size


class _SpaceSentinel:
    """Stand-in for ``jax.memory.Space`` members on single-memory backends."""

    def __init__(self, name):
        self._name = name

    def __repr__(self):
        return f"MemorySpace({self._name})"


def _ensure_memory_space():
    if hasattr(jax, "memory") and hasattr(jax, "typeof"):
        return

    device = _SpaceSentinel("Device")
    host = _SpaceSentinel("Host")

    if not hasattr(jax, "memory"):
        memory = types.SimpleNamespace(
            Space=types.SimpleNamespace(Device=device, Host=host))
        jax.memory = memory
    else:  # pragma: no cover - memory exists but typeof missing
        device = jax.memory.Space.Device
        host = jax.memory.Space.Host

    if not hasattr(jax, "typeof"):
        _everything_on_device = types.SimpleNamespace(memory_space=device)

        def typeof(x):
            del x
            return _everything_on_device

        jax.typeof = typeof

    # device_put(x, Space.*) degrades to identity: one memory space means the
    # host/device distinction carries no information, and numerics are
    # unchanged (staging vjps become identities).
    _orig_device_put = jax.device_put

    def device_put(x, device_or_space=None, *args, **kwargs):
        if device_or_space is device or device_or_space is host:
            return x
        return _orig_device_put(x, device_or_space, *args, **kwargs)

    jax.device_put = device_put


def host_memory_kind():
    """``"pinned_host"`` when supported by the default device, else ``None``."""
    try:
        dev = jax.devices()[0]
        kinds = {m.kind for m in dev.addressable_memories()}
    except Exception:
        return "pinned_host"
    return "pinned_host" if "pinned_host" in kinds else None


_ensure_shard_map()
_ensure_axis_size()
_ensure_memory_space()
