"""Tokenizer loading for real HF checkpoint directories.

The reference serves real models end-to-end with user-supplied HF
tokenizers (MII pipelines around FastGen; v1 checkpoint loading
reference inference/engine.py:303). This module is the framework-native
equivalent for the ``dstpu generate`` path: read ``tokenizer.json``
(the fast-tokenizer format every modern release ships) straight from the
model dir via the local ``tokenizers`` runtime — no network, no
``transformers`` dependency at serve time.

SentencePiece-only checkpoints (``tokenizer.model`` without a
``tokenizer.json``) are rejected with a clear message — the environment
ships no sentencepiece runtime; re-export the tokenizer with
``AutoTokenizer(...).save_pretrained`` (writes tokenizer.json) first.
"""

import json
import os
from typing import List, Optional, Sequence

import numpy as np


class HFTokenizer:
    """Thin wrapper: encode/decode + special-token ids from the model dir."""

    def __init__(self, model_dir: str):
        tok_path = os.path.join(model_dir, "tokenizer.json")
        if not os.path.isfile(tok_path):
            if os.path.isfile(os.path.join(model_dir, "tokenizer.model")):
                raise FileNotFoundError(
                    f"{model_dir} ships only a sentencepiece tokenizer.model; "
                    "this environment has no sentencepiece runtime — save the "
                    "fast-tokenizer form (tokenizer.json) into the dir first"
                )
            raise FileNotFoundError(f"no tokenizer.json under {model_dir}")
        from tokenizers import Tokenizer

        self._tok = Tokenizer.from_file(tok_path)
        self.bos_token_id = None
        self.eos_token_id = None
        self._read_special_ids(model_dir)

    def _read_special_ids(self, model_dir: str):
        """bos/eos resolution order: generation_config.json, config.json,
        tokenizer_config.json token strings mapped through the vocab."""
        for fname, bos_key, eos_key in (
            ("generation_config.json", "bos_token_id", "eos_token_id"),
            ("config.json", "bos_token_id", "eos_token_id"),
        ):
            path = os.path.join(model_dir, fname)
            if not os.path.isfile(path):
                continue
            cfg = json.load(open(path))
            if self.bos_token_id is None and cfg.get(bos_key) is not None:
                self.bos_token_id = int(
                    cfg[bos_key][0] if isinstance(cfg[bos_key], list) else cfg[bos_key]
                )
            if self.eos_token_id is None and cfg.get(eos_key) is not None:
                self.eos_token_id = int(
                    cfg[eos_key][0] if isinstance(cfg[eos_key], list) else cfg[eos_key]
                )
        tc_path = os.path.join(model_dir, "tokenizer_config.json")
        if os.path.isfile(tc_path) and (self.bos_token_id is None or self.eos_token_id is None):
            tc = json.load(open(tc_path))

            def to_id(entry):
                if entry is None:
                    return None
                s = entry["content"] if isinstance(entry, dict) else str(entry)
                return self._tok.token_to_id(s)

            if self.bos_token_id is None:
                self.bos_token_id = to_id(tc.get("bos_token"))
            if self.eos_token_id is None:
                self.eos_token_id = to_id(tc.get("eos_token"))

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    def encode(self, text: str, add_bos: bool = True) -> np.ndarray:
        ids = self._tok.encode(text).ids
        if add_bos and self.bos_token_id is not None and (
            not ids or ids[0] != self.bos_token_id
        ):
            ids = [self.bos_token_id] + ids
        return np.asarray(ids, np.int32)

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode([int(i) for i in ids], skip_special_tokens=skip_special_tokens)


def load_tokenizer(model_dir: str) -> HFTokenizer:
    return HFTokenizer(model_dir)
