"""Environment report (reference ``deepspeed/env_report.py`` + ``bin/ds_report``).

Prints the software stack, visible accelerators, and per-op availability —
the TPU analogue of the reference's op-compatibility table (its green/red
``[OKAY]/[NO]`` rows per CUDA op builder).
"""

import importlib
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


def _try_version(mod: str) -> str:
    try:
        m = importlib.import_module(mod)
        return getattr(m, "__version__", "unknown")
    except Exception:
        return None


def op_report():
    """Per-op availability, mirroring the reference's builder table."""
    from deepspeed_tpu.ops.op_builder import ALL_OPS

    rows = []
    for name, builder in sorted(ALL_OPS.items()):
        try:
            compatible = builder().is_compatible()
        except Exception:
            compatible = False
        rows.append((name, compatible))
    return rows


def main():
    import deepspeed_tpu

    print("-" * 60)
    print("DeepSpeed-TPU environment report")
    print("-" * 60)
    print(f"python ................ {sys.version.split()[0]}")
    print(f"deepspeed_tpu ......... {deepspeed_tpu.__version__}")
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        v = _try_version(mod)
        print(f"{mod:<22}{'.' * max(1, 22 - len(mod))} {v if v else RED_NO}")
    print("-" * 60)
    try:
        import jax

        print(f"backend ............... {jax.default_backend()}")
        for d in jax.devices():
            kind = getattr(d, "device_kind", "?")
            print(f"  device {d.id}: {kind}")
        try:
            stats = jax.devices()[0].memory_stats() or {}
            lim = stats.get("bytes_limit")
            if lim:
                print(f"  hbm bytes_limit: {lim / 2**30:.2f} GiB")
        except Exception:
            pass
    except Exception as e:
        print(f"jax devices ........... {RED_NO} ({e})")
    print("-" * 60)
    print("op availability:")
    for name, ok in op_report():
        print(f"  {name:<28} {GREEN_OK if ok else RED_NO}")
    print("-" * 60)
    return 0


if __name__ == "__main__":
    sys.exit(main())
