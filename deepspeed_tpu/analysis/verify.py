"""Tier-B compile-time verifier: donation-alias coverage + recompile counts.

Tier A's ``donate-arity`` rule proves the *indices* line up with the
signature; this module proves the *compiled artifact* actually aliases
every declared donated buffer. It lowers the repo's jitted entry points on
CPU with representative (tiny-model) arguments and checks, per declared
donated input, that the lowered module carries ``tf.aliasing_output`` for
it — the annotation XLA turns into ``input_output_alias``. A donated
buffer that fails to alias (shape/dtype drifted from the output, or the
index points at the wrong argument) is a silent full-buffer copy per step:
the exact class of bug the split-step's ``donate_argnums=(13, 14)``
off-by-one would have been.

It also counts retraces: a fixed-shape entry point that traces more than
once across representative same-shape calls is quietly recompiling on the
hot path (weak-typed scalars, python-hash-unstable statics, ...).

Entry points covered (the compiled hot paths every perf PR leans on):
  * ``engine_v2`` row step, split step, fused multistep decode
  * ``runtime.engine`` fused ZeRO-3 train step (bucketed-collective overlap)
  * ``runtime.streamed_adam`` per-leaf donated update
  * quantized-collective variants: TP decode through the int8 psum islands,
    pipelined train step through int8 ppermute activation sends
  * tiled-overlap variants (``comm_overlap="tiled"``): tp2 decode through
    the per-tile ppermute rings, ZeRO-3 train step through tiled
    prefetch-bucket all-gathers
  * tiered-KV readmit (``import_kv_blocks_chunked``): the double-buffered
    host→HBM window scatter, bf16 and int8 pools

Run via ``dstpu lint --verify`` (wired into tools/run_smoke.sh).
"""

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "CheckResult",
    "DonatedBuffer",
    "check_donation",
    "check_recompile",
    "run_verify",
    "verify_disagg",
    "verify_elastic",
    "verify_engine_v2",
    "verify_host_tier",
    "verify_lock_order",
    "verify_quantized_comm",
    "verify_ring_train",
    "verify_splash",
    "verify_streamed_adam",
    "verify_tiled_overlap",
    "verify_train_engine",
]


@dataclass
class DonatedBuffer:
    flat_index: int
    shape: Tuple[int, ...]
    dtype: str
    aliased: bool

    def render(self) -> str:
        mark = "aliased" if self.aliased else "NOT ALIASED"
        return f"arg[{self.flat_index}] {self.dtype}{list(self.shape)}: {mark}"


@dataclass
class CheckResult:
    name: str
    kind: str  # "donation" | "recompile"
    ok: bool
    detail: str = ""
    buffers: List[DonatedBuffer] = field(default_factory=list)

    def render(self) -> str:
        status = "ok" if self.ok else "FAIL"
        line = f"[{status}] {self.kind}: {self.name}"
        if self.detail:
            line += f" — {self.detail}"
        return line

    def to_dict(self) -> dict:
        return {
            "name": self.name, "kind": self.kind, "ok": self.ok,
            "detail": self.detail,
            "buffers": [
                {"flat_index": b.flat_index, "shape": list(b.shape),
                 "dtype": b.dtype, "aliased": b.aliased}
                for b in self.buffers
            ],
        }


# ---------------------------------------------------------------------------
# core checks
# ---------------------------------------------------------------------------
def _alias_positions(lowered_text: str) -> Dict[int, bool]:
    """Lowered-module position -> carries tf.aliasing_output. Positions are
    the KEPT flat inputs in order (jit drops unused arguments)."""
    try:
        sig = lowered_text.split("@main(", 1)[1]
    except IndexError:
        return {}
    end = sig.find(") ->")
    if end == -1:
        end = sig.find(")")
    sig = sig[:end]
    out = {}
    # Split on the argument markers instead of regex-matching each attr
    # dict: attr values (mhlo.sharding strings under a mesh) contain nested
    # braces a non-recursive pattern cannot span.
    parts = re.split(r"%arg(\d+):", sig)
    for i in range(1, len(parts) - 1, 2):
        out[int(parts[i])] = "tf.aliasing_output" in parts[i + 1]
    return out


def _donor_positions(lowered_text: str) -> Dict[int, bool]:
    """Lowered-module position -> carries ``jax.buffer_donor``. Under
    committed input shardings (TP engines, mesh train steps) jit defers the
    donated-input → output match to XLA and emits this attribute instead of
    ``tf.aliasing_output``; the lowering text alone under-reports donation
    there."""
    try:
        sig = lowered_text.split("@main(", 1)[1]
    except IndexError:
        return {}
    end = sig.find(") ->")
    if end == -1:
        end = sig.find(")")
    sig = sig[:end]
    out = {}
    parts = re.split(r"%arg(\d+):", sig)
    for i in range(1, len(parts) - 1, 2):
        out[int(parts[i])] = "jax.buffer_donor" in parts[i + 1]
    return out


def _compiled_alias_params(lowered) -> set:
    """Parameter indices XLA actually aliased, from the compiled module's
    ``input_output_alias`` header — the ground truth the buffer-donor path
    resolves to at compile time."""
    try:
        hlo = lowered.compile().as_text()
    except Exception:
        return set()
    start = hlo.find("input_output_alias={")
    if start == -1:
        return set()
    i = start + len("input_output_alias=")
    depth = 0
    block = ""
    for j in range(i, len(hlo)):
        ch = hlo[j]
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                block = hlo[i:j + 1]
                break
    return {int(m) for m in re.findall(r"\((\d+),", block)}


def _arg_info(lowered):
    """Flat (donated, shape, dtype) per input, in flattening order."""
    import jax

    leaves = jax.tree_util.tree_leaves(lowered.args_info)
    out = []
    for ai in leaves:
        shape = tuple(getattr(ai, "shape", ()) or ())
        dtype = str(getattr(ai, "dtype", "?"))
        out.append((bool(ai.donated), shape, dtype))
    return out


def _kept_indices(lowered, n_flat: int) -> List[int]:
    kept = None
    try:
        kept = lowered._lowering.compile_args.get("kept_var_idx")
    except AttributeError:
        pass
    return sorted(kept) if kept is not None else list(range(n_flat))


def check_donation(name: str, jitted, args: Sequence, kwargs: Optional[dict] = None,
                   lowered=None) -> CheckResult:
    """Lower ``jitted(*args)`` and verify every declared donated input is
    aliased to an output in the lowered module."""
    import warnings

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        low = lowered if lowered is not None else jitted.lower(*args, **(kwargs or {}))
        info = _arg_info(low)
        text = low.as_text()
        alias_by_pos = _alias_positions(text)
        donor_by_pos = _donor_positions(text)
    kept = _kept_indices(low, len(info))
    pos_of = {flat: pos for pos, flat in enumerate(kept)}

    buffers = []
    compiled_alias = None  # lazy: only compiled when a buffer-donor arg shows up
    via_donor = 0
    for i, (donated, shape, dtype) in enumerate(info):
        if not donated:
            continue
        pos = pos_of.get(i)
        aliased = pos is not None and alias_by_pos.get(pos, False)
        if not aliased and pos is not None and donor_by_pos.get(pos, False):
            if compiled_alias is None:
                compiled_alias = _compiled_alias_params(low)
            aliased = pos in compiled_alias
            via_donor += aliased
        buffers.append(DonatedBuffer(i, shape, dtype, aliased))

    missing = [b for b in buffers if not b.aliased]
    notes = [str(w.message).splitlines()[0] for w in caught
             if "donated" in str(w.message).lower()]
    if not buffers:
        return CheckResult(name, "donation", False,
                           "no donated inputs declared — donation annotation lost", buffers)
    if missing:
        detail = "; ".join(b.render() for b in missing)
        if notes:
            detail += " | " + "; ".join(notes)
        return CheckResult(name, "donation", False, detail, buffers)
    detail = f"{len(buffers)} donated buffer(s) all aliased"
    if via_donor:
        detail += f" ({via_donor} resolved via XLA buffer-donor)"
    return CheckResult(name, "donation", True, detail, buffers)


def check_recompile(name: str, jitted, max_traces: int = 1) -> CheckResult:
    """A fixed-shape entry point must trace once across representative
    calls; every extra cache entry is a silent recompile on the hot path."""
    try:
        n = jitted._cache_size()
    except AttributeError:
        return CheckResult(name, "recompile", True, "cache size unavailable; skipped")
    ok = n <= max_traces
    return CheckResult(
        name, "recompile", ok,
        f"{n} compiled variant(s) after representative calls (max {max_traces})")


# ---------------------------------------------------------------------------
# entry-point harnesses (tiny models, CPU)
# ---------------------------------------------------------------------------
def _capture_builder(obj, attr: str, store: dict, key: str):
    """Shadow a lazy jit-builder method on one instance so the first real
    call records (compiled_fn, concrete_args) without changing behavior."""
    orig = getattr(obj, attr)

    def build(*bargs, **bkw):
        fn = orig(*bargs, **bkw)

        def call(*args):
            store.setdefault(key, (fn, args))
            return fn(*args)

        return call

    setattr(obj, attr, build)


def _tiny_v2_engine(decode_steps: int = 2, kv_dtype: str = "bf16",
                    kv_extra: Optional[dict] = None):
    import jax

    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import get_config, init_params

    cfg = get_config("tiny", n_layers=2, dtype="float32", max_seq_len=512)
    params = init_params(cfg, jax.random.key(0))
    kv = {"block_size": 4, "num_blocks": 128, "max_blocks_per_seq": 32,
          "kv_cache_dtype": kv_dtype}
    kv.update(kv_extra or {})
    rc = RaggedInferenceEngineConfig.from_dict({
        "dtype": "float32",
        "decode_steps": decode_steps,
        "kv_cache": kv,
        "state_manager": {"max_tracked_sequences": 16,
                          "max_ragged_batch_size": 256,
                          "max_ragged_sequence_count": 4, "max_context": 256},
    })
    return cfg, InferenceEngineV2(cfg, params, rc)


def _engine_v2_pass(kv_dtype: str) -> List[CheckResult]:
    """One donation/recompile sweep over the v2 serving programs for a pool
    payload dtype. int8 mode appends the fp32 scale planes as donated
    trailing args on every step — the exact new-leaf case where a wrong
    variadic index would silently copy a full plane per step, so both
    dtypes get the full sweep."""
    import jax.numpy as jnp
    import numpy as np

    tag = "" if kv_dtype == "bf16" else f"[{kv_dtype}]"
    results: List[CheckResult] = []
    cfg, eng = _tiny_v2_engine(kv_dtype=kv_dtype)
    captured: dict = {}
    _capture_builder(eng, "_build_split_step", captured, "split_step")
    _capture_builder(eng, "_build_multistep_decode", captured, "multistep_decode")

    def prompts(seed):
        rng = np.random.default_rng(seed)
        return [rng.integers(1, cfg.vocab_size, size=(12,)).astype(np.int32)
                for _ in range(2)]

    # two same-shape passes: pass 1 traces, pass 2 must hit the caches
    eng.generate(prompts(0), max_new_tokens=6)
    eng.generate(prompts(1), max_new_tokens=6)

    for key, label in (("split_step", f"engine_v2.split_step{tag}"),
                       ("multistep_decode", f"engine_v2.multistep_decode{tag}")):
        if key not in captured:
            results.append(CheckResult(label, "donation", False,
                                       "entry point never executed in harness"))
            continue
        fn, args = captured[key]
        results.append(check_donation(label, fn, args))
        results.append(check_recompile(label, fn))

    # row step (per-row baseline path): lower directly with config shapes.
    # int8 appends the donated scale planes (argnums 7, 8).
    kv = eng.config.kv_cache
    fn = eng._build_row_step(8)
    row_args = (
        eng.params,
        jnp.zeros((1, 8), jnp.int32),
        jnp.int32(0),
        jnp.int32(8),
        jnp.zeros((kv.max_blocks_per_seq,), jnp.int32),
        eng._k_cache,
        eng._v_cache,
    ) + eng._scale_args()
    results.append(check_donation(f"engine_v2.row_step{tag}", fn, row_args))

    # speculative verify step (serving/spec): the K+1-token draft-and-verify
    # program declares both KV pools donated — without aliasing, every spec
    # round would copy the whole paged pool, erasing the subsystem's win.
    # Lowering reads shapes only, so passing the live pools is safe (same
    # precedent as row_step above).
    R = eng.config.state_manager.max_ragged_sequence_count
    fn = eng._build_verify_step(4)
    verify_args = (
        eng.params,
        jnp.zeros((R, 5), jnp.int32),
        jnp.zeros((R,), jnp.int32),
        jnp.zeros((R, kv.max_blocks_per_seq), jnp.int32),
        jnp.zeros((R,), jnp.int32),
        jnp.zeros((R,), jnp.bool_),
        jnp.ones((R,), jnp.int32),
        eng._rng,
        jnp.float32(1.0),
        eng._k_cache,
        eng._v_cache,
    ) + eng._scale_args()
    results.append(check_donation(f"engine_v2.verify_step{tag}", fn, verify_args))
    return results


def verify_engine_v2() -> List[CheckResult]:
    # both pool payload dtypes: int8 adds donated scale-plane leaves to
    # every serving program (split, multistep, verify)
    return _engine_v2_pass("bf16") + _engine_v2_pass("int8")


def verify_streamed_adam() -> List[CheckResult]:
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.streamed_adam import StreamedAdamW

    opt = StreamedAdamW(chunk_elems=64, overlap=True)
    fn = opt._leaf_jit(quantized=False)

    def args():
        # param is bf16 as in real training: with an fp32 param the updated
        # param equals the fp32 master output bit-for-bit, XLA emits one
        # tensor for both outputs, and only one donated input can back it.
        return (
            jnp.zeros((128,), jnp.float32),    # grad
            jnp.ones((128,), jnp.float32),     # master
            jnp.zeros((128,), jnp.float32),    # mu
            jnp.zeros((128,), jnp.float32),    # nu
            jnp.ones((128,), jnp.bfloat16),    # param
            jnp.float32(1e-3),
            jnp.int32(1),
        )

    results = [check_donation("streamed_adam.leaf_step", fn, args())]
    fn(*args())
    fn(*args())
    results.append(check_recompile("streamed_adam.leaf_step", fn))
    return results


def _mlp_loss(params, batch):
    import jax
    import jax.numpy as jnp

    h = batch["x"]
    n = len(params)
    for i in range(n):
        layer = params[f"layer_{i}"]
        h = h @ layer["w"] + layer["b"]
        if i < n - 1:
            h = jax.nn.relu(h)
    return jnp.mean(jnp.square(h - batch["y"]))


def verify_train_engine() -> List[CheckResult]:
    """ZeRO-3 + bucketed-collective overlap train step (the runtime/zero/
    overlap.py machinery) on a W-way virtual CPU mesh."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu

    W = 8 if len(jax.devices()) >= 8 else 1
    key = jax.random.key(0)
    keys = jax.random.split(key, 2)
    params = {
        f"layer_{i}": {
            "w": (jax.random.normal(keys[i], (16, 16)) * 0.1).astype(jnp.float32),
            "b": jnp.zeros((16,), jnp.float32),
        }
        for i in range(2)
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=_mlp_loss,
        model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3, "param_persistence_threshold": 0},
            "mesh": {"data": W},
            "steps_per_print": 10**9,
        },
    )
    captured: dict = {}
    _capture_builder(engine, "_build_train_step", captured, "train_step")

    rng = np.random.default_rng(0)

    def batch():
        x = rng.normal(size=(8 * W, 16)).astype(np.float32)
        return {"x": x, "y": (x * 0.5).astype(np.float32)}

    engine.train_batch(batch=batch())
    engine.train_batch(batch=batch())

    name = "runtime.engine.train_step[zero3+overlap]"
    results: List[CheckResult] = []
    if "train_step" not in captured:
        return [CheckResult(name, "donation", False,
                            "train step never executed in harness")]
    fn, args = captured["train_step"]
    results.append(check_donation(name, fn, args))

    # The first call traces against the engine's unsharded init params;
    # donation hands back zero3-sharded outputs, so call 2 legitimately
    # traces once more. Steady state = no cache growth after that warmup.
    try:
        warm = fn._cache_size()
    except AttributeError:
        results.append(CheckResult(name, "recompile", True,
                                   "cache size unavailable; skipped"))
        return results
    engine.train_batch(batch=batch())
    n = fn._cache_size()
    results.append(CheckResult(
        name, "recompile", n <= warm and warm <= 2,
        f"{n} compiled variant(s) at steady state "
        f"(warmup {warm}: trace 2 picks up the zero3-sharded donated outputs)"))
    return results


def verify_ring_train() -> List[CheckResult]:
    """Train step through the context-parallel ring attention path
    (ops/attention/sharded.ring_flash_attention) on a data×context virtual
    CPU mesh. The ring body runs inside shard_map with a custom_vjp whose
    residuals cross the shard boundary — exactly where a donated buffer can
    silently lose its alias (the XLA annotation must survive the shard_map
    lowering, not just the outer jit), so the donation check runs against
    the full sharded step artifact."""
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, init_params, make_loss_fn

    if len(jax.devices()) < 8:
        return [CheckResult("runtime.engine.train_step[ring-cp]", "donation",
                            True, "needs 8 devices; skipped")]
    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, n_layers=2, n_heads=4, max_seq_len=64,
        dtype="float32", attention_impl="flash_ring",
    )
    params = init_params(cfg, jax.random.key(0))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=make_loss_fn(cfg),
        model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0},
            "mesh": {"data": 2, "context": 4},
            "steps_per_print": 10**9,
        },
    )
    captured: dict = {}
    _capture_builder(engine, "_build_train_step", captured, "train_step")
    toks = np.random.default_rng(0).integers(0, 64, size=(4, 65)).astype(np.int32)
    engine.train_batch(batch={"input_ids": toks})
    engine.train_batch(batch={"input_ids": toks})

    name = "runtime.engine.train_step[ring-cp]"
    if "train_step" not in captured:
        return [CheckResult(name, "donation", False,
                            "train step never executed in harness")]
    fn, args = captured["train_step"]
    return [check_donation(name, fn, args)]


def verify_quantized_comm() -> List[CheckResult]:
    """Donation coverage for the ``comm_quant="int8"`` step artifacts: the
    serving TP decode programs routed through the quantized-psum shard_map
    islands, and the pipelined train step whose inter-stage activation sends
    ride ``quantized_ppermute``. Each quantized wire rebuilds its payload as
    int8 + fp32 block scales inside shard_map — fresh intermediates sitting
    next to the donated KV pools and grad buffers, exactly where an alias
    annotation can fail to survive the lowering — so both quantized steps
    get the full donation check against the compiled artifact."""
    import jax
    import numpy as np

    from deepspeed_tpu.parallel.topology import (
        Topology,
        reset_topology,
        set_topology,
    )

    if len(jax.devices()) < 8:
        return [CheckResult("quantized_comm", "donation", True,
                            "needs 8 devices; skipped")]

    results: List[CheckResult] = []

    # --- TP decode: int8 psum behind attention-out / MLP-down projections --
    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import get_config, init_params

    reset_topology()
    try:
        set_topology(Topology(data=4, model=2))
        cfg = get_config("tiny", n_layers=2, dtype="float32", max_seq_len=512)
        params = init_params(cfg, jax.random.key(0))
        rc = RaggedInferenceEngineConfig.from_dict({
            "dtype": "float32",
            "tp_size": 2,
            "comm_quant": "int8",
            "decode_steps": 2,
            "kv_cache": {"block_size": 4, "num_blocks": 128,
                         "max_blocks_per_seq": 32},
            "state_manager": {"max_tracked_sequences": 16,
                              "max_ragged_batch_size": 256,
                              "max_ragged_sequence_count": 4,
                              "max_context": 256},
        })
        eng = InferenceEngineV2(cfg, params, rc)
        captured: dict = {}
        _capture_builder(eng, "_build_split_step", captured, "split_step")
        _capture_builder(eng, "_build_multistep_decode", captured,
                         "multistep_decode")

        def prompts(seed):
            rng = np.random.default_rng(seed)
            return [rng.integers(1, cfg.vocab_size, size=(12,)).astype(np.int32)
                    for _ in range(2)]

        eng.generate(prompts(0), max_new_tokens=6)
        eng.generate(prompts(1), max_new_tokens=6)
        # call 1 traces against host arrays; donation hands back sharded
        # outputs, so call 2 legitimately traces once more (same warmup as
        # verify_train_engine). Steady state = no growth on pass 3.
        warm = {k: v[0]._cache_size() for k, v in captured.items()
                if hasattr(v[0], "_cache_size")}
        eng.generate(prompts(2), max_new_tokens=6)
        for key, label in (
            ("split_step", "engine_v2.split_step[tp2+commq8]"),
            ("multistep_decode", "engine_v2.multistep_decode[tp2+commq8]"),
        ):
            if key not in captured:
                results.append(CheckResult(
                    label, "donation", False,
                    "entry point never executed in harness"))
                continue
            fn, args = captured[key]
            results.append(check_donation(label, fn, args))
            if key not in warm:
                results.append(CheckResult(label, "recompile", True,
                                           "cache size unavailable; skipped"))
                continue
            n = fn._cache_size()
            results.append(CheckResult(
                label, "recompile", n <= warm[key] and warm[key] <= 2,
                f"{n} compiled variant(s) at steady state "
                f"(warmup {warm[key]}: trace 2 picks up the sharded donated "
                "outputs)"))
    finally:
        reset_topology()

    # --- pipelined train step: int8 inter-stage activation sends -----------
    import deepspeed_tpu
    from deepspeed_tpu.runtime.pipe import (
        make_pipelined_loss_fn,
        pipeline_partition_specs,
    )

    try:
        topo = Topology(pipe=2, data=2, model=2)
        set_topology(topo)
        cfg = get_config("tiny", n_layers=4, dtype="float32", remat=False)
        params = init_params(cfg, jax.random.key(0))
        loss_fn = make_pipelined_loss_fn(cfg, micro_batches=2, topo=topo,
                                         comm_quant="int8")
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=loss_fn,
            model_parameters=params,
            mpu=topo,
            config={
                "train_batch_size": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
                "zero_optimization": {"stage": 1},
                "steps_per_print": 10**9,
            },
            param_specs=pipeline_partition_specs(cfg, topo),
        )
        captured2: dict = {}
        _capture_builder(engine, "_build_train_step", captured2, "train_step")
        toks = np.random.default_rng(0).integers(
            0, cfg.vocab_size, size=(4, 33)).astype(np.int32)
        engine.train_batch(batch={"input_ids": toks})
        engine.train_batch(batch={"input_ids": toks})

        name = "runtime.engine.train_step[pipe2+commq8]"
        if "train_step" not in captured2:
            results.append(CheckResult(name, "donation", False,
                                       "train step never executed in harness"))
        else:
            fn, args = captured2["train_step"]
            results.append(check_donation(name, fn, args))
    finally:
        reset_topology()
    return results


def verify_tiled_overlap() -> List[CheckResult]:
    """Donation coverage for the ``comm_overlap="tiled"`` step artifacts:
    the tp2 serving decode whose row wires decompose into per-tile ppermute
    rings (comm/overlap_tiled.py), and the ZeRO-3 train step whose prefetch
    bucket all-gathers split into per-tile collectives. Each tile's ring
    builds fresh per-chunk intermediates inside shard_map right next to the
    donated KV pools / grad buffers — more lowering surface between the
    donation annotation and the compiled alias than the monolithic wire, so
    both tiled steps get the full donation check."""
    import jax
    import numpy as np

    from deepspeed_tpu.parallel.topology import (
        Topology,
        reset_topology,
        set_topology,
    )

    if len(jax.devices()) < 8:
        return [CheckResult("tiled_overlap", "donation", True,
                            "needs 8 devices; skipped")]

    results: List[CheckResult] = []

    # --- TP decode: per-tile rings behind attention-out / MLP-down ---------
    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import get_config, init_params

    reset_topology()
    try:
        set_topology(Topology(data=4, model=2))
        cfg = get_config("tiny", n_layers=2, dtype="float32", max_seq_len=512)
        params = init_params(cfg, jax.random.key(0))
        rc = RaggedInferenceEngineConfig.from_dict({
            "dtype": "float32",
            "tp_size": 2,
            "comm_overlap": "tiled",
            "tp_overlap_tiles": 2,
            "decode_steps": 2,
            "kv_cache": {"block_size": 4, "num_blocks": 128,
                         "max_blocks_per_seq": 32},
            "state_manager": {"max_tracked_sequences": 16,
                              "max_ragged_batch_size": 256,
                              "max_ragged_sequence_count": 4,
                              "max_context": 256},
        })
        eng = InferenceEngineV2(cfg, params, rc)
        captured: dict = {}
        _capture_builder(eng, "_build_split_step", captured, "split_step")
        _capture_builder(eng, "_build_multistep_decode", captured,
                         "multistep_decode")

        def prompts(seed):
            rng = np.random.default_rng(seed)
            return [rng.integers(1, cfg.vocab_size, size=(12,)).astype(np.int32)
                    for _ in range(2)]

        eng.generate(prompts(0), max_new_tokens=6)
        eng.generate(prompts(1), max_new_tokens=6)
        for key, label in (
            ("split_step", "engine_v2.split_step[tp2+tiled]"),
            ("multistep_decode", "engine_v2.multistep_decode[tp2+tiled]"),
        ):
            if key not in captured:
                results.append(CheckResult(
                    label, "donation", False,
                    "entry point never executed in harness"))
                continue
            fn, args = captured[key]
            results.append(check_donation(label, fn, args))
    finally:
        reset_topology()

    # --- ZeRO-3 train step: tiled prefetch-bucket all-gathers --------------
    import deepspeed_tpu
    import jax.numpy as jnp

    W = 8
    key = jax.random.key(0)
    keys = jax.random.split(key, 2)
    params = {
        f"layer_{i}": {
            "w": (jax.random.normal(keys[i], (16, 16)) * 0.1).astype(jnp.float32),
            "b": jnp.zeros((16,), jnp.float32),
        }
        for i in range(2)
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=_mlp_loss,
        model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3, "param_persistence_threshold": 0},
            "comm_overlap": "tiled",
            "tp_overlap_tiles": 2,
            "mesh": {"data": W},
            "steps_per_print": 10**9,
        },
    )
    captured2: dict = {}
    _capture_builder(engine, "_build_train_step", captured2, "train_step")
    rng = np.random.default_rng(0)

    def batch():
        x = rng.normal(size=(8 * W, 16)).astype(np.float32)
        return {"x": x, "y": (x * 0.5).astype(np.float32)}

    engine.train_batch(batch=batch())
    engine.train_batch(batch=batch())

    name = "runtime.engine.train_step[zero3+tiled]"
    if "train_step" not in captured2:
        results.append(CheckResult(name, "donation", False,
                                   "train step never executed in harness"))
    else:
        fn, args = captured2["train_step"]
        results.append(check_donation(name, fn, args))
    return results


def verify_disagg() -> List[CheckResult]:
    """Disaggregated serving: the Router's extracted scheduling loop must
    leave each engine's donated step programs intact. The prefill worker's
    split step and the decode replicas' fused decode rounds both consume
    and reassign the donated KV pools, and the KV-handoff import path
    reassigns them too (``import_kv_blocks`` scatter) — a broken donation
    here would copy a full paged pool every step on every replica."""
    import numpy as np

    from deepspeed_tpu.serving.cluster import Router
    from deepspeed_tpu.serving.request import SamplingParams

    results: List[CheckResult] = []
    engines = [_tiny_v2_engine(decode_steps=2)[1] for _ in range(3)]
    captured: dict = {}
    _capture_builder(engines[0], "_build_split_step", captured, "split")
    for eng in engines[1:]:
        # both replicas store under one key; setdefault keeps the first
        _capture_builder(eng, "_build_multistep_decode", captured, "multistep")
    router = Router(engines=engines, num_prefill_workers=1,
                    decode_steps=2).start()
    try:
        reqs = [
            router.submit(
                np.arange(1 + i, 13 + i, dtype=np.int32),
                params=SamplingParams(max_new_tokens=6, ignore_eos=True),
            )
            for i in range(4)
        ]
        for r in reqs:
            if not r.wait(300):
                raise RuntimeError("disagg verify request did not finish")
    finally:
        router.shutdown()
    for key, label in (("split", "disagg.prefill_split_step"),
                       ("multistep", "disagg.decode_multistep")):
        if key not in captured:
            results.append(CheckResult(label, "donation", False,
                                       "entry point never executed under the router"))
            continue
        fn, args = captured[key]
        results.append(check_donation(label, fn, args))
        results.append(check_recompile(label, fn))
    return results


def verify_host_tier() -> List[CheckResult]:
    """Tiered-KV re-import (``engine_v2.import_kv_blocks_chunked``): the
    double-buffered window scatter must keep the pool donated (a lost alias
    copies the full paged pool once per window, per readmitted prefix) and
    must compile exactly once per plane family — the tail window pads its
    index vector with the trash row and zero-fills values precisely so the
    shapes never vary. bf16 pools scatter one (payload) shape; int8 pools
    add the fp32 scale-plane shape, so their steady state is two cache
    entries, not one."""
    import jax.numpy as jnp
    import numpy as np

    results: List[CheckResult] = []
    for kv_dtype, max_traces in (("bf16", 1), ("int8", 2)):
        tag = "" if kv_dtype == "bf16" else f"[{kv_dtype}]"
        label = f"engine_v2.kv_readmit{tag}"
        _, eng = _tiny_v2_engine(kv_dtype=kv_dtype, kv_extra={
            "prefix_cache": True,
            "host_tier_bytes": 1 << 20,
            "host_tier_chunk_blocks": 2,
        })
        blocks = [1, 2, 3, 4, 5]  # 5 blocks @ chunk 2 -> 3 windows, padded tail
        payload = eng.export_kv_blocks(blocks)
        # two identical chunked imports: pass 1 traces, pass 2 must hit the
        # cache — any growth is a per-window recompile on the readmit path
        eng.import_kv_blocks_chunked(blocks, payload, chunk_blocks=2)
        eng.import_kv_blocks_chunked(blocks, payload, chunk_blocks=2)
        fn = eng._kv_readmit_jit
        if fn is None:
            results.append(CheckResult(
                label, "donation", False,
                "chunked import never built the readmit scatter"))
            continue
        pool = eng._k_cache
        vals = jnp.zeros((pool.shape[0], 2) + tuple(pool.shape[2:]), pool.dtype)
        results.append(check_donation(
            label, fn, (pool, jnp.zeros((2,), jnp.int32), vals)))
        results.append(check_recompile(label, fn, max_traces=max_traces))
    return results


def verify_kv_transport() -> List[CheckResult]:
    """Zero-copy KV handoff wire (``export_kv_blocks_windows`` +
    ``import_kv_blocks_device``): the pipelined device transport must ride
    the SAME compiled programs as the host-tier readmit path — a fixed
    chunk-window export gather that traces once per plane family, and the
    donated ``_kv_readmit_jit`` scatter (a lost alias would copy the whole
    paged pool once per in-flight window, per handoff). The tp=2 leg
    re-lays each window onto the decode replica's head-sharded mesh via
    ``device_put`` before the scatter; the donated sharded import must
    still alias and must not retrace per window."""
    import jax
    import jax.numpy as jnp

    results: List[CheckResult] = []
    engines = {}  # kv_dtype -> tp1 engine, reused as the tp2 leg's source
    for kv_dtype, max_traces in (("bf16", 1), ("int8", 2)):
        tag = "" if kv_dtype == "bf16" else f"[{kv_dtype}]"
        _, eng = _tiny_v2_engine(kv_dtype=kv_dtype)
        engines[kv_dtype] = eng
        blocks = [1, 2, 3, 4, 5]  # 5 blocks @ chunk 2 -> 3 windows, padded tail
        # round 1 traces; round 2 (with a covered prefix, redirected to the
        # trash row — NOT a narrower scatter) must hit both caches
        wins, ch = eng.export_kv_blocks_windows(blocks, chunk_blocks=2)
        eng.import_kv_blocks_device(blocks, wins, ch)
        wins, ch = eng.export_kv_blocks_windows(blocks, chunk_blocks=2)
        eng.import_kv_blocks_device(blocks, wins, ch, skip_blocks=2)
        gather = eng._kv_export_jit
        if gather is None:
            results.append(CheckResult(
                f"engine_v2.kv_export{tag}", "recompile", False,
                "windowed export never built the gather"))
        else:
            results.append(check_recompile(
                f"engine_v2.kv_export{tag}", gather, max_traces=max_traces))
        fn = eng._kv_readmit_jit
        label = f"engine_v2.kv_device_import{tag}"
        if fn is None:
            results.append(CheckResult(
                label, "donation", False,
                "device import never built the readmit scatter"))
            continue
        pool = eng._k_cache
        vals = jnp.zeros((pool.shape[0], 2) + tuple(pool.shape[2:]), pool.dtype)
        results.append(check_donation(
            label, fn, (pool, jnp.zeros((2,), jnp.int32), vals)))
        results.append(check_recompile(label, fn, max_traces=max_traces))

    # --- tp=2 decode replica: head-sharded import off a tp=1 export --------
    if len(jax.devices()) < 8:
        results.append(CheckResult("kv_transport[tp2]", "donation", True,
                                   "needs 8 devices; skipped"))
        return results

    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import get_config, init_params
    from deepspeed_tpu.parallel.topology import (
        Topology,
        reset_topology,
        set_topology,
    )

    reset_topology()
    try:
        set_topology(Topology(data=4, model=2))
        for kv_dtype, max_traces in (("bf16", 1), ("int8", 2)):
            tag = f"[tp2,{kv_dtype}]"
            cfg = get_config("tiny", n_layers=2, dtype="float32",
                             max_seq_len=512)
            params = init_params(cfg, jax.random.key(0))
            rc = RaggedInferenceEngineConfig.from_dict({
                "dtype": "float32",
                "tp_size": 2,
                "decode_steps": 2,
                "kv_cache": {"block_size": 4, "num_blocks": 128,
                             "max_blocks_per_seq": 32,
                             "kv_cache_dtype": kv_dtype},
                "state_manager": {"max_tracked_sequences": 16,
                                  "max_ragged_batch_size": 256,
                                  "max_ragged_sequence_count": 4,
                                  "max_context": 256},
            })
            dst = InferenceEngineV2(cfg, params, rc)
            src = engines[kv_dtype]  # tp=1 exporter (the prefill side)
            blocks = [1, 2, 3, 4, 5]
            wins, ch = src.export_kv_blocks_windows(blocks, chunk_blocks=2)
            dst.import_kv_blocks_device(blocks, wins, ch)
            wins, ch = src.export_kv_blocks_windows(blocks, chunk_blocks=2)
            dst.import_kv_blocks_device(blocks, wins, ch, skip_blocks=2)
            fn = dst._kv_readmit_jit
            label = f"engine_v2.kv_device_import{tag}"
            if fn is None:
                results.append(CheckResult(
                    label, "donation", False,
                    "sharded device import never built the readmit scatter"))
                continue
            pool = dst._k_cache
            vals = jax.device_put(
                jnp.zeros((pool.shape[0], 2) + tuple(pool.shape[2:]),
                          pool.dtype),
                dst._kv_sharding)
            results.append(check_donation(
                label, fn, (pool, jnp.zeros((2,), jnp.int32), vals)))
            results.append(check_recompile(label, fn, max_traces=max_traces))
    finally:
        reset_topology()
    return results


def verify_elastic() -> List[CheckResult]:
    """Elastic serving: a warm spare's ``warm_trace`` must cover EVERY step
    program the serving loop drives, so post-warm serving traffic — prefill,
    fused decode rounds, and the preempt-checkpoint resume import — runs
    entirely inside the jit caches (zero admission-time compiles), and a
    preempted-then-resumed greedy stream must replay bit-identically to the
    uninterrupted one (content-addressed sampling + exact KV cursor
    restore)."""
    import numpy as np

    from deepspeed_tpu.serving.elastic import (
        WarmSparePool,
        assert_no_new_traces,
        preempt_sequence,
        resume_sequence,
    )

    results: List[CheckResult] = []

    # -- warm spare: serving-shaped traffic after warm_trace is compile-free
    pool = WarmSparePool(
        factory=lambda: _tiny_v2_engine(decode_steps=2)[1],
        count=1,
        warm_kw={"decode_steps": 2, "spec_k": 0},
    )
    eng, baseline = pool.acquire()
    sched = eng.scheduler
    uid = 7
    sched.submit(uid, np.arange(1, 13, dtype=np.int32))
    tok = None
    for _ in range(8):
        out = eng.step_tokens()
        if uid in out:
            tok = out[uid]
            break
    sched.feedback(uid, tok)
    for _ in range(3):
        eng.decode_round(2)
    label = "elastic.warm_spare"
    try:
        assert_no_new_traces(eng, baseline, label=label)
        results.append(CheckResult(
            label, "recompile", True,
            f"{len(baseline)} warmed program(s), zero new traces under "
            "serving traffic"))
    except RuntimeError as e:
        results.append(CheckResult(label, "recompile", False, str(e)))

    # -- preempt → resume on the warm engine: KV-cursor restore is exact
    # and the resumed stream continues the same greedy tokens; the resume
    # import must also stay inside the warmed caches
    seq = eng.state_manager.get_sequence(uid)
    pre_tokens = list(seq.tokens)
    ho = preempt_sequence(eng, uid)
    sched.finish(uid)
    resume_sequence(eng, ho)
    seq2 = eng.state_manager.get_sequence(uid)
    label = "elastic.preempt_resume"
    ok = (list(seq2.tokens) == pre_tokens
          and int(seq2.seen_tokens) == len(pre_tokens) - 1)
    results.append(CheckResult(
        label, "parity", ok,
        "checkpoint restored token history + KV cursor exactly" if ok
        else f"history/cursor drifted: {len(seq2.tokens)} tokens, "
             f"cursor {seq2.seen_tokens} (want {len(pre_tokens)} / "
             f"{len(pre_tokens) - 1})"))
    for _ in range(2):
        eng.decode_round(2)
    label = "elastic.resume_no_retrace"
    try:
        assert_no_new_traces(eng, baseline, label=label)
        results.append(CheckResult(
            label, "recompile", True,
            "resume import + post-resume decode hit the warmed caches"))
    except RuntimeError as e:
        results.append(CheckResult(label, "recompile", False, str(e)))
    sched.finish(uid)

    # the warmed split program itself must be single-trace per bucket
    for key, fn in getattr(eng, "_split_jit", {}).items():
        results.append(check_recompile(f"elastic.split_step[tq={key}]", fn))
    return results


def verify_lock_order() -> List[CheckResult]:
    """Lock discipline, both halves (see ``analysis/locks.py`` +
    ``analysis/lockwitness.py``): the static whole-tree acquisition graph
    must be acyclic with no reentrancy hazards, and the chaos smoke
    scenario — the nastiest concurrent path the repo has (worker kill
    mid-stream, faulted handoff import, faulted peer pull, recovery +
    probation on a 2-replica router) — run under the runtime witness must
    observe no inversion and no acquisition order the static model does
    not declare. A subgraph failure means either the model's inference
    misses a call path (annotate it) or the code broke the documented
    hierarchy (docs/ANALYSIS.md)."""
    import os
    import sys

    from deepspeed_tpu.analysis import locks
    from deepspeed_tpu.analysis.lockwitness import (
        LockOrderViolation,
        witness_locks,
    )

    results: List[CheckResult] = []
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    model = locks.build_model_from_paths([pkg_dir])

    cycles = model.cycles()
    hazards = model.reentrant_hazards
    static_ok = not cycles and not hazards
    detail = (f"{len(model.order_edges)} acquisition edge(s), acyclic, "
              f"no reentrancy hazards")
    if cycles:
        detail = "cycle(s): " + "; ".join(
            " -> ".join(c + [c[0]]) for c in cycles)
    elif hazards:
        detail = "reentrancy hazard(s): " + "; ".join(
            f"{key} at {site.path}:{site.line} ({why})"
            for key, site, why in hazards)
    results.append(CheckResult("lock_model.static", "lock-order",
                               static_ok, detail))

    # the runtime half replays the chaos gate's scenario; it needs the test
    # fixtures importable (repo root on sys.path — true under run_smoke.sh
    # and pytest, restored if this runs from an installed copy)
    repo_root = os.path.dirname(pkg_dir)
    added = repo_root not in sys.path
    if added:
        sys.path.insert(0, repo_root)
    try:
        import numpy as np

        from deepspeed_tpu.serving import Router, SamplingParams
        from deepspeed_tpu.serving.resilience import (
            FaultSpec,
            ResilienceConfig,
            inject,
        )
        from tests.unit.test_serving import FakeEngine, _expected_tokens
    except ImportError as e:
        results.append(CheckResult(
            "lock_witness.chaos_smoke", "lock-order", True,
            f"test fixtures unavailable ({e}); witness run skipped"))
        return results
    finally:
        if added:
            sys.path.remove(repo_root)

    prompts = [np.arange(1 + 10 * i, 6 + 10 * i, dtype=np.int32)
               for i in range(6)]
    want = [_expected_tokens(p, 20) for p in prompts]
    schedule = (
        FaultSpec("worker.crash", nth=10, replica="d0"),
        FaultSpec("handoff.import", nth=2),
        FaultSpec("peer_pull", nth=1),
    )
    cfg = ResilienceConfig(hung_step_s=2.0, probe_backoff_s=0.05,
                           retry_backoff_s=0.001)
    with witness_locks() as wit:  # record-only: assert after the run
        with inject(*schedule):
            router = Router(
                engines=[FakeEngine(step_delay=0.001) for _ in range(2)],
                num_prefill_workers=0, resilience=cfg).start()
            try:
                reqs = [router.submit(p, params=SamplingParams(
                            max_new_tokens=20, ignore_eos=True))
                        for p in prompts]
                for r in reqs:
                    if not r.wait(60):
                        results.append(CheckResult(
                            "lock_witness.chaos_smoke", "lock-order", False,
                            f"scenario wedged: uid={r.uid} never finished "
                            f"({r.state})"))
                        return results
                for r, w in zip(reqs, want):
                    if list(r.generated) != w:
                        results.append(CheckResult(
                            "lock_witness.chaos_smoke", "lock-order", False,
                            f"recovery diverged for uid={r.uid} — witness "
                            "run is not the scenario it claims to cover"))
                        return results
            finally:
                router.shutdown()

    observed = wit.graph()
    static_edges = model.edge_closure() | set(model.order_edges)
    try:
        wit.assert_subgraph(static_edges)
        results.append(CheckResult(
            "lock_witness.chaos_smoke", "lock-order", True,
            f"{len(observed)} observed edge(s) across "
            f"{sum(observed.values())} nested acquisition(s), no inversion, "
            f"all within the static model"))
    except LockOrderViolation as e:
        results.append(CheckResult(
            "lock_witness.chaos_smoke", "lock-order", False, str(e)))
    return results


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------
def verify_splash() -> List[CheckResult]:
    """Splash scheduled sparse attention through the model train step: the
    step donates its params, reaches steady state in ONE compiled program,
    and the block schedule is a trace-time constant — retracing hits the
    lru cache instead of rebuilding it."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.models import transformer as T
    from deepspeed_tpu.ops.attention.core import _derived_splash_schedule

    cfg = T.get_config("tiny", n_layers=2, dtype="float32", max_seq_len=256,
                       attention_impl="splash", sliding_window=96)
    tok = jnp.zeros((2, 256), jnp.int32)

    def step(params, tokens):
        def loss(p):
            logits, aux = T.forward(p, tokens, cfg)
            return jnp.mean(jnp.square(logits.astype(jnp.float32))) + aux

        grads = jax.grad(loss)(params)
        return jax.tree.map(lambda p, g: p - 1e-3 * g, params, grads)

    fn = jax.jit(step, donate_argnums=(0,))
    results = [check_donation(
        "splash.train_step", fn, (T.init_params(cfg, jax.random.key(0)), tok))]

    # committed params (device_put) so step 1's host-staged signature equals
    # the steady state — exactly how a real trainer holds them
    p = jax.device_put(T.init_params(cfg, jax.random.key(0)), jax.devices()[0])
    before = _derived_splash_schedule.cache_info()
    for _ in range(3):
        p = fn(p, tok)
    results.append(check_recompile("splash.train_step", fn))

    # trace-time-constant schedule: however many times the step traces or
    # runs, the schedule is BUILT at most once more (first trace) and then
    # served from the lru cache — never rebuilt per step
    after = _derived_splash_schedule.cache_info()
    ok = after.misses <= before.misses + 1
    results.append(CheckResult(
        "splash.schedule_constant", "recompile", ok,
        f"schedule builds {before.misses}->{after.misses} across 3 steps "
        "(<=1 new build: a trace-time constant, not per-step work)"))
    return results


def run_verify(verbose: bool = True) -> Tuple[List[CheckResult], bool]:
    """Run every entry-point harness; returns (results, all_ok). Harness
    crashes surface as failed results, never as silent skips."""
    results: List[CheckResult] = []
    for fn, label in (
        (verify_engine_v2, "engine_v2"),
        (verify_streamed_adam, "streamed_adam"),
        (verify_train_engine, "train_engine"),
        (verify_ring_train, "ring_train"),
        (verify_quantized_comm, "quantized_comm"),
        (verify_tiled_overlap, "tiled_overlap"),
        (verify_disagg, "disagg"),
        (verify_host_tier, "host_tier"),
        (verify_kv_transport, "kv_transport"),
        (verify_elastic, "elastic"),
        (verify_splash, "splash"),
        (verify_lock_order, "lock_order"),
    ):
        try:
            results.extend(fn())
        except Exception as e:  # harness must report, not die mid-suite
            results.append(CheckResult(label, "donation", False,
                                       f"harness error: {type(e).__name__}: {e}"))
    ok = all(r.ok for r in results)
    if verbose:
        for r in results:
            print(r.render())
        print(f"dstpu verify: {sum(r.ok for r in results)}/{len(results)} checks passed")
    return results, ok
