"""``dstpu lint`` — CLI for the two-tier static-analysis suite.

    dstpu lint deepspeed_tpu/                 # Tier A rules, human output
    dstpu lint deepspeed_tpu/ --format json   # machine-readable
    dstpu lint --verify                       # Tier B compile-time verifier
    dstpu lint deepspeed_tpu/ --verify --fail-on error   # the CI gate

Exit code: 1 when any Tier-A finding is at or above ``--fail-on``
(default: error), or any Tier-B check fails; 0 otherwise.
Also runnable as ``python -m deepspeed_tpu.analysis.cli``.
"""

import argparse
import os
import sys


def _default_lint_root() -> str:
    # the package tree itself: lint what ships
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dstpu lint",
        description="JAX-aware static analysis (Tier A: AST rules; "
                    "Tier B: compile-time donation/recompile verifier)")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint (default: the "
                             "deepspeed_tpu package, unless --verify alone)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--fail-on", choices=("error", "warning", "never"),
                        default="error",
                        help="minimum severity that makes the exit code "
                             "nonzero (default: error)")
    parser.add_argument("--select", action="append", default=None,
                        metavar="RULE", help="run only the named rule(s)")
    parser.add_argument("--ignore", action="append", default=None,
                        metavar="RULE", help="skip the named rule(s)")
    parser.add_argument("--hot-prefix", action="append", default=None,
                        metavar="FRAG",
                        help="path fragment marking a hot module for the "
                             "host-sync rule (default: serving/, "
                             "inference/v2/, runtime/zero/)")
    parser.add_argument("--verify", action="store_true",
                        help="run the Tier-B compile-time verifier "
                             "(lowers jitted entry points on CPU)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    from deepspeed_tpu.analysis import framework

    if args.list_rules:
        for rule in framework.resolve_rules():
            print(f"{rule.name:28s} [{rule.severity}] {rule.description}")
        return 0

    paths = args.paths
    if not paths and not args.verify:
        paths = [_default_lint_root()]

    findings = []
    tree_ctx_out = []
    if paths:
        try:
            findings = framework.run_lint(
                paths,
                select=args.select,
                ignore=args.ignore,
                hot_prefixes=tuple(args.hot_prefix) if args.hot_prefix
                else framework.DEFAULT_HOT_PREFIXES,
                tree_ctx_out=tree_ctx_out,
            )
        except KeyError as e:
            print(f"dstpu lint: {e.args[0]}", file=sys.stderr)
            return 2

    verify_results, verify_ok = [], True
    if args.verify:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        from deepspeed_tpu.analysis.verify import run_verify

        verify_results, verify_ok = run_verify(verbose=(args.format == "text"))

    if args.format == "json":
        # the lock model rides along for editor/CI integrations: lock
        # registry, guarded attributes, and the acquisition graph
        model_doc = (tree_ctx_out[0].lock_model.to_doc()
                     if tree_ctx_out else None)
        print(framework.render_json(
            findings,
            verify=[r.to_dict() for r in verify_results] if args.verify else None,
            model=model_doc))
    elif paths:
        print(framework.render_text(findings))

    rc = 0
    if args.fail_on != "never":
        threshold = framework.SEVERITIES.index(args.fail_on)
        if any(framework.SEVERITIES.index(f.severity) >= threshold for f in findings):
            rc = 1
    if not verify_ok:
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(lint_main())
