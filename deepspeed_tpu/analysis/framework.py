"""Tier-A rule framework for ``dstpu lint``.

Pure-AST static analysis: no jax import, no code execution, so the linter
runs in any environment (pre-commit hooks, CI containers without
accelerators) in well under a second for the whole package.

Concepts
--------
* ``Rule`` — a named check with a default severity. ``check(ctx)`` yields
  ``Finding``s for one parsed file.
* ``REGISTRY`` — rules register themselves at import time (see
  ``analysis.rules``); ``run_lint`` runs every registered rule unless a
  ``select`` subset is given.
* suppression — ``# dstpu: noqa`` silences every rule on that line,
  ``# dstpu: noqa[rule-a,rule-b]`` silences the named rules only. The
  comment goes on the *first* line of the flagged statement.
* hot modules — some rules (host-sync) only apply to latency-critical
  subtrees; ``LintContext.hot_module`` is computed from ``hot_prefixes``
  path fragments (default: serving/, inference/v2/, runtime/zero/).
"""

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence

SEVERITIES = ("info", "warning", "error")

#: path fragments marking latency-critical subtrees (host-sync rule scope)
DEFAULT_HOT_PREFIXES = (
    "serving/", "inference/v2/", "runtime/zero/", "ops/sparse_attention/",
)

_NOQA_RE = re.compile(r"#\s*dstpu:\s*noqa(?:\[([^\]]*)\])?", re.IGNORECASE)


@dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str
    #: last line of the flagged statement (== line for single-line nodes);
    #: lets editor integrations span highlights
    end_line: int = 0

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "end_line": self.end_line or self.line,
            "message": self.message,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.severity}] {self.rule}: {self.message}"


class Rule:
    """Base class for lint rules. Subclasses set ``name``, ``severity``,
    ``description`` and implement ``check``."""

    name: str = ""
    severity: str = "error"
    description: str = ""

    def check(self, ctx: "LintContext") -> Iterable[Finding]:
        raise NotImplementedError


REGISTRY: Dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator: instantiate and add to the registry."""
    rule = rule_cls()
    if not rule.name:
        raise ValueError(f"rule {rule_cls.__name__} has no name")
    if rule.severity not in SEVERITIES:
        raise ValueError(f"rule {rule.name}: bad severity {rule.severity!r}")
    if rule.name in REGISTRY:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    REGISTRY[rule.name] = rule
    return rule_cls


class TreeContext:
    """Whole-run state shared by every file's :class:`LintContext`: the
    parsed ``(path, text, tree)`` triples and the lazily-built lock model
    (``analysis/locks.py``). Building is deferred until a rule asks, so
    runs that select no lock-discipline rule pay nothing."""

    def __init__(self, files):
        self.files = files  # List[Tuple[path, text, tree]]
        self._lock_model = None

    @property
    def lock_model(self):
        if self._lock_model is None:
            from deepspeed_tpu.analysis import locks
            self._lock_model = locks.build_model(self.files)
        return self._lock_model


class LintContext:
    """Everything a rule needs to analyze one file."""

    def __init__(self, path: str, text: str, tree: ast.AST,
                 hot_prefixes: Sequence[str] = DEFAULT_HOT_PREFIXES,
                 tree_ctx: Optional[TreeContext] = None):
        self.path = path
        self.text = text
        self.tree = tree
        norm = path.replace(os.sep, "/")
        self.hot_module = any(frag in norm for frag in hot_prefixes)
        self._noqa = _collect_noqa(text)
        # standalone lint_file() calls get a single-file tree context so
        # model-backed rules still work (cross-file edges just won't exist)
        self.tree_ctx = tree_ctx or TreeContext([(path, text, tree)])

    @property
    def lock_model(self):
        return self.tree_ctx.lock_model

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self._noqa.get(line)
        return rules is not None and ("*" in rules or rule in rules)

    def finding(self, rule: "Rule", node, message: str,
                severity: Optional[str] = None) -> Optional[Finding]:
        """Build a Finding for an AST node (or int line), honoring noqa.
        Returns None when suppressed."""
        line = node if isinstance(node, int) else getattr(node, "lineno", 0)
        col = 0 if isinstance(node, int) else getattr(node, "col_offset", 0)
        end = line if isinstance(node, int) else (
            getattr(node, "end_lineno", None) or line)
        if self.suppressed(rule.name, line):
            return None
        return Finding(
            rule=rule.name,
            severity=severity or rule.severity,
            path=self.path,
            line=line,
            col=col,
            message=message,
            end_line=end,
        )


def _collect_noqa(text: str) -> Dict[int, set]:
    """Map line number -> suppressed rule names ({'*'} = all). Uses the
    tokenizer so noqa markers inside string literals don't count."""
    out: Dict[int, set] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(text).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _NOQA_RE.search(tok.string)
            if not m:
                continue
            names = m.group(1)
            rules = (
                {r.strip() for r in names.split(",") if r.strip()}
                if names is not None
                else {"*"}
            )
            out.setdefault(tok.start[0], set()).update(rules)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # unparseable files surface as parse-error findings elsewhere
        pass
    return out


# ---------------------------------------------------------------------------
# running
# ---------------------------------------------------------------------------
def iter_py_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", "build", ".eggs")
                )
                files.extend(
                    os.path.join(root, n) for n in sorted(names) if n.endswith(".py")
                )
        elif p.endswith(".py"):
            files.append(p)
    return files


def resolve_rules(select: Optional[Sequence[str]] = None,
                  ignore: Optional[Sequence[str]] = None) -> List[Rule]:
    # rule modules self-register on first import
    from deepspeed_tpu.analysis import rules as _rules  # noqa: F401

    names = list(select) if select else sorted(REGISTRY)
    unknown = [n for n in names if n not in REGISTRY]
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)}; known: {', '.join(sorted(REGISTRY))}")
    if ignore:
        names = [n for n in names if n not in set(ignore)]
    return [REGISTRY[n] for n in names]


def _load_source(path: str):
    """(text, tree, error Finding | None) for one file."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        return None, None, Finding("parse-error", "error", path, 0, 0,
                                   f"cannot read: {e}")
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as e:
        return text, None, Finding("parse-error", "error", path,
                                   e.lineno or 0, e.offset or 0,
                                   f"syntax error: {e.msg}")
    return text, tree, None


def lint_file(path: str, rules: Sequence[Rule],
              hot_prefixes: Sequence[str] = DEFAULT_HOT_PREFIXES,
              tree_ctx: Optional[TreeContext] = None) -> List[Finding]:
    text, tree, err = _load_source(path)
    if err is not None:
        return [err]
    ctx = LintContext(path, text, tree, hot_prefixes=hot_prefixes,
                      tree_ctx=tree_ctx)
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(f for f in rule.check(ctx) if f is not None)
    return findings


def run_lint(paths: Sequence[str],
             select: Optional[Sequence[str]] = None,
             ignore: Optional[Sequence[str]] = None,
             hot_prefixes: Sequence[str] = DEFAULT_HOT_PREFIXES,
             tree_ctx_out: Optional[list] = None) -> List[Finding]:
    rules = resolve_rules(select, ignore)
    findings: List[Finding] = []
    # parse everything up front: whole-tree rules (lock discipline) need
    # every class in the run visible before the first file is checked
    sources = []
    for path in iter_py_files(paths):
        text, tree, err = _load_source(path)
        if err is not None:
            findings.append(err)
        else:
            sources.append((path, text, tree))
    tree_ctx = TreeContext(sources)
    if tree_ctx_out is not None:
        tree_ctx_out.append(tree_ctx)
    for path, text, tree in sources:
        ctx = LintContext(path, text, tree, hot_prefixes=hot_prefixes,
                          tree_ctx=tree_ctx)
        for rule in rules:
            findings.extend(f for f in rule.check(ctx) if f is not None)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ---------------------------------------------------------------------------
# output
# ---------------------------------------------------------------------------
def severity_counts(findings: Sequence[Finding]) -> Dict[str, int]:
    counts = {s: 0 for s in SEVERITIES}
    for f in findings:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    return counts


def render_text(findings: Sequence[Finding]) -> str:
    lines = [f.render() for f in findings]
    counts = severity_counts(findings)
    lines.append(
        f"dstpu lint: {len(findings)} finding(s) "
        f"({counts['error']} error, {counts['warning']} warning, {counts['info']} info)"
    )
    return "\n".join(lines)


def render_json(findings: Sequence[Finding], verify: Optional[list] = None,
                model: Optional[dict] = None) -> str:
    doc = {
        "version": 1,
        "findings": [f.to_dict() for f in findings],
        "counts": severity_counts(findings),
    }
    if verify is not None:
        doc["verify"] = verify
    if model is not None:
        doc["model"] = model
    return json.dumps(doc, indent=2, sort_keys=True)


def max_severity(findings: Sequence[Finding]) -> Optional[str]:
    worst = None
    for f in findings:
        if worst is None or SEVERITIES.index(f.severity) > SEVERITIES.index(worst):
            worst = f.severity
    return worst
