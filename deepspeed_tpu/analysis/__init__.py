"""dstpu static-analysis subsystem.

Tier A (``framework`` + ``rules``): pure-AST lint rules, no jax import —
see ``dstpu lint`` / ``python -m deepspeed_tpu.analysis.cli``.
Tier B (``verify``): compile-time donation-alias and recompile verification
of the repo's jitted entry points (imports jax, runs on CPU).
"""

from deepspeed_tpu.analysis.framework import (  # noqa: F401
    DEFAULT_HOT_PREFIXES,
    Finding,
    REGISTRY,
    Rule,
    register,
    render_json,
    render_text,
    run_lint,
)
