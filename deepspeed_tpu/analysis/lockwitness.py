"""Runtime lock-order witness — the dynamic half of the lock-discipline
pass.

The static model (``analysis/locks.py``) proves the *declared* order is
acyclic; this module observes the order threads *actually* acquire locks
in and checks the two agree. ``WitnessLock``/``WitnessCondition`` wrap
the real primitives, recording per-thread acquisition stacks into a
process-global order graph:

* every acquisition of ``B`` while the thread holds ``A`` adds the edge
  ``A -> B`` (reentrant re-acquisition of the same lock adds nothing);
* the moment both ``A -> B`` and ``B -> A`` have been observed the graph
  has an inversion — a real interleaving away from deadlock. In
  ``raise_on_inversion`` mode the acquiring thread gets a
  :class:`LockOrderViolation` on the spot (tests); otherwise the
  inversion is recorded for the post-run assertion (verify runs, where
  raising inside a router worker would wedge the scenario under test);
* :meth:`WitnessState.assert_subgraph` checks every observed edge embeds
  in the static model's transitive closure — the runtime scenario never
  exercised an ordering the static contract does not declare.

``witness_locks()`` is the drop-in: a context manager that wraps the
serving classes' ``__init__`` so every ``threading.Lock`` / ``RLock`` /
``Condition`` attribute created at construction is replaced with its
witness wrapper, named ``ClassName.attr`` to match the static model's
lock keys. Instances built *inside* the context are witnessed; existing
instances can be added with :func:`wrap_instance`.
"""

import threading
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = [
    "LockOrderViolation",
    "WitnessCondition",
    "WitnessLock",
    "WitnessState",
    "witness_locks",
    "wrap_instance",
]

_LOCK_TYPE = type(threading.Lock())
_RLOCK_TYPE = type(threading.RLock())


class LockOrderViolation(RuntimeError):
    """Two locks were observed acquired in both orders."""


class WitnessState:
    """Process-global observation state shared by every witness wrapper.

    Thread-safe; the held-stack is thread-local, the order graph is
    guarded by an internal mutex (which is never held while user code
    runs, so the witness itself cannot deadlock the program under test).
    """

    def __init__(self, raise_on_inversion: bool = True):
        self.raise_on_inversion = raise_on_inversion
        self._mu = threading.Lock()
        #: observed (held, acquired) -> acquisition count
        self.edges: Dict[Tuple[str, str], int] = {}
        #: inversions seen: (a, b) with both (a, b) and (b, a) observed
        self.inversions: List[Tuple[str, str]] = []
        self._tls = threading.local()

    # -- per-thread stack ---------------------------------------------------
    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def held(self) -> Tuple[str, ...]:
        """The current thread's held-lock names, outermost first."""
        return tuple(self._stack())

    # -- recording ----------------------------------------------------------
    def on_acquired(self, name: str) -> None:
        """Called by a wrapper AFTER its real acquire succeeded."""
        stack = self._stack()
        outer = [n for n in stack if n != name]
        reentrant = name in stack
        stack.append(name)
        if reentrant or not outer:
            return
        inverted = None
        with self._mu:
            for h in dict.fromkeys(outer):  # dedupe, keep order
                self.edges[(h, name)] = self.edges.get((h, name), 0) + 1
                if (name, h) in self.edges:
                    pair = (name, h)
                    if pair not in self.inversions:
                        self.inversions.append(pair)
                    inverted = h
        if inverted is not None and self.raise_on_inversion:
            raise LockOrderViolation(
                f"lock-order inversion: acquired {name} while holding "
                f"{inverted}, but {inverted} has also been acquired while "
                f"holding {name}")

    def on_released(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    # -- results ------------------------------------------------------------
    def graph(self) -> Dict[Tuple[str, str], int]:
        with self._mu:
            return dict(self.edges)

    def assert_no_inversion(self) -> None:
        with self._mu:
            inversions = list(self.inversions)
        if inversions:
            rendered = ", ".join(f"{a} <-> {b}" for a, b in inversions)
            raise LockOrderViolation(
                f"observed lock-order inversion(s): {rendered}")

    def assert_subgraph(self, static_edges: Iterable[Tuple[str, str]],
                        ignore: Iterable[str] = ()) -> None:
        """Every observed edge must lie in ``static_edges`` (pass the
        static model's ``edge_closure() | set(order_edges)``). Edges
        touching a lock named in ``ignore`` are skipped (locks the static
        model deliberately does not track, e.g. test doubles)."""
        self.assert_no_inversion()
        static = set(static_edges)
        skip = set(ignore)
        missing = sorted(
            (a, b) for (a, b) in self.graph()
            if (a, b) not in static and a not in skip and b not in skip)
        if missing:
            rendered = ", ".join(f"{a} -> {b}" for a, b in missing)
            raise LockOrderViolation(
                f"observed acquisition order not declared by the static "
                f"lock model: {rendered}; either the model's inference "
                f"misses the call path (annotate it) or the code violates "
                f"the documented hierarchy (docs/ANALYSIS.md)")


class WitnessLock:
    """Drop-in wrapper for ``Lock``/``RLock`` reporting to a
    :class:`WitnessState` under a stable name (``ClassName.attr``)."""

    def __init__(self, inner, name: str, state: WitnessState):
        self._inner = inner
        self.name = name
        self._state = state

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._state.on_acquired(self.name)
        return got

    def release(self) -> None:
        self._state.on_released(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def __repr__(self):
        return f"<WitnessLock {self.name} of {self._inner!r}>"


class WitnessCondition(WitnessLock):
    """Witness wrapper for ``threading.Condition``. ``wait``/``wait_for``
    release the lock for their duration, so the held-stack drops the name
    across the wait and re-enters on wakeup (re-adding edges against any
    locks still held — correctly: waking up re-acquires)."""

    def wait(self, timeout: Optional[float] = None):
        self._state.on_released(self.name)
        try:
            return self._inner.wait(timeout)
        finally:
            self._state.on_acquired(self.name)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self._state.on_released(self.name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._state.on_acquired(self.name)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()

    def __repr__(self):
        return f"<WitnessCondition {self.name} of {self._inner!r}>"


def wrap_instance(obj, state: WitnessState,
                  cls_name: Optional[str] = None) -> List[str]:
    """Replace every lock/condition attribute of ``obj`` with its witness
    wrapper (idempotent); returns the wrapped lock names."""
    name = cls_name or type(obj).__name__
    wrapped = []
    for attr, val in list(vars(obj).items()):
        key = f"{name}.{attr}"
        if isinstance(val, threading.Condition):
            setattr(obj, attr, WitnessCondition(val, key, state))
        elif isinstance(val, (_LOCK_TYPE, _RLOCK_TYPE)):
            setattr(obj, attr, WitnessLock(val, key, state))
        else:
            continue
        wrapped.append(key)
    return wrapped


def _default_classes() -> List[type]:
    """The serving control plane's lock-owning classes (mirrors the static
    model's registry over ``deepspeed_tpu/serving`` + observability)."""
    from deepspeed_tpu.observability.events import EventLog
    from deepspeed_tpu.observability.tracing import SpanTracer
    from deepspeed_tpu.serving.cluster.core import EngineCore
    from deepspeed_tpu.serving.cluster.router import Router
    from deepspeed_tpu.serving.driver import ServingDriver
    from deepspeed_tpu.serving.elastic.spares import WarmSparePool
    from deepspeed_tpu.serving.metrics import ServingMetrics
    from deepspeed_tpu.serving.net.endpoint import KVEndpoint
    from deepspeed_tpu.serving.net.flow import CreditWindow
    from deepspeed_tpu.serving.resilience.faults import FaultInjector
    from deepspeed_tpu.serving.resilience.health import ReplicaHealth
    from deepspeed_tpu.serving.streaming import TokenStream

    return [Router, EngineCore, ServingDriver, TokenStream, CreditWindow,
            KVEndpoint, ServingMetrics, ReplicaHealth, FaultInjector,
            WarmSparePool, SpanTracer, EventLog]


@contextmanager
def witness_locks(classes: Optional[Iterable[type]] = None,
                  raise_on_inversion: bool = False,
                  state: Optional[WitnessState] = None):
    """Monkeypatch ``__init__`` of ``classes`` (default: the serving
    control plane) so instances constructed inside the context get their
    lock attributes replaced with witness wrappers. Yields the
    :class:`WitnessState`; restores the classes on exit.

    Default is record-only (``raise_on_inversion=False``): an inversion
    raised inside a router worker thread would wedge the scenario under
    test — call :meth:`WitnessState.assert_subgraph` (or
    ``assert_no_inversion``) after the run instead. Pass
    ``raise_on_inversion=True`` in unit tests that drive the locks
    directly and want the raise at the faulty acquisition site.
    """
    st = state if state is not None else WitnessState(raise_on_inversion)
    cls_list = list(classes) if classes is not None else _default_classes()
    originals: Dict[type, object] = {}

    def _make_init(cls, orig):
        def __init__(self, *args, **kwargs):
            orig(self, *args, **kwargs)
            # named after the declaring class so keys match the static
            # model even for subclass instances; wrap_instance is
            # idempotent, so chained wrapped __init__s are safe
            wrap_instance(self, st, cls.__name__)
        __init__._witness_wrapped = True  # marker for debugging
        return __init__

    for cls in cls_list:
        originals[cls] = cls.__init__
        cls.__init__ = _make_init(cls, originals[cls])
    try:
        yield st
    finally:
        for cls, orig in originals.items():
            cls.__init__ = orig
