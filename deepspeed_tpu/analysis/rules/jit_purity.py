"""Rule ``impure-jit``: Python side effects and impure RNG inside
jit-decorated functions.

``random.*`` / ``np.random.*`` / ``time.*`` run ONCE at trace time and bake
a constant into the compiled program — every subsequent call replays the
same "random" number or timestamp, the classic silent-staleness tracer
hazard. ``print`` runs at trace time only (use ``jax.debug.print``).
``jax.random`` is the sanctioned in-program RNG and is not flagged.
"""

import ast

from deepspeed_tpu.analysis.framework import Rule, register
from deepspeed_tpu.analysis.rules._common import (
    ScopeResolver,
    dotted_name,
    is_jax_jit,
    partial_jit_kwargs,
)

_TIME_MODULES = {"time", "_time"}
_RNG_MODULES = {"random", "np.random", "numpy.random", "_random"}


@register
class ImpureJitRule(Rule):
    name = "impure-jit"
    severity = "error"
    description = (
        "impure call (random.*, np.random.*, time.*, print) inside a jitted "
        "function executes at trace time only and bakes a constant into the "
        "compiled program"
    )

    def check(self, ctx):
        rule = self
        jitted = []  # function nodes handed to jax.jit

        class Collect(ScopeResolver):
            def handle_call(self, call):
                if is_jax_jit(call.func):
                    fn = self.resolve_jit_target(call)
                    if fn is not None:
                        jitted.append(fn)

            def handle_functiondef(self, node):
                for dec in node.decorator_list:
                    if is_jax_jit(dec):
                        jitted.append(node)
                    elif isinstance(dec, ast.Call) and (
                            is_jax_jit(dec.func) or partial_jit_kwargs(dec) is not None):
                        jitted.append(node)

        Collect().visit(ctx.tree)

        findings = []
        seen_lines = set()
        for fn in jitted:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = _impure_message(node)
                if msg and node.lineno not in seen_lines:
                    seen_lines.add(node.lineno)
                    findings.append(ctx.finding(rule, node, msg))
        return findings


def _impure_message(call: ast.Call):
    name = dotted_name(call.func)
    if name is None:
        return None
    if name == "print":
        return ("print() inside a jitted function runs at trace time only; "
                "use jax.debug.print for runtime output")
    mod = name.rsplit(".", 1)[0] if "." in name else None
    if mod in _TIME_MODULES:
        return (f"{name}() inside a jitted function is evaluated once at "
                f"trace time and frozen into the program")
    if mod in _RNG_MODULES or name in _RNG_MODULES:
        return (f"{name}() inside a jitted function draws ONE value at trace "
                f"time and replays it every call; thread a jax.random key "
                f"instead")
    return None
