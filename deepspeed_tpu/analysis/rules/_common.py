"""Shared AST helpers for the rule modules: jax.jit call detection,
scope-aware function resolution, and jitted-function discovery."""

import ast
from typing import Dict, List, Optional, Tuple, Union

FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute(Name('jax'), 'jit'); None for anything
    that is not a plain dotted name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_jax_jit(node: ast.AST) -> bool:
    """Matches ``jax.jit`` and bare ``jit`` (from jax import jit)."""
    name = dotted_name(node)
    return name in ("jax.jit", "jit")


def jit_call_kwargs(call: ast.Call) -> Dict[str, ast.expr]:
    return {kw.arg: kw.value for kw in call.keywords if kw.arg is not None}


def partial_jit_kwargs(call: ast.Call) -> Optional[Dict[str, ast.expr]]:
    """``partial(jax.jit, donate_argnums=...)`` / ``functools.partial(...)``
    -> its keyword dict; None when not a jit partial."""
    name = dotted_name(call.func)
    if name not in ("partial", "functools.partial"):
        return None
    if call.args and is_jax_jit(call.args[0]):
        return jit_call_kwargs(call)
    return None


def const_argnums(node: Optional[ast.expr]) -> Optional[List[int]]:
    """Literal donate_argnums/static_argnums value -> list of ints; None
    when absent or not statically resolvable."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return out
    return None


def positional_arity(fn: FuncNode) -> Tuple[int, bool]:
    """(number of positional parameters, has *args)."""
    a = fn.args
    return len(a.posonlyargs) + len(a.args), a.vararg is not None


def func_label(fn: FuncNode) -> str:
    return getattr(fn, "name", "<lambda>")


class ScopeResolver(ast.NodeVisitor):
    """Source-order walk that keeps a stack of lexical scopes mapping
    names to their FunctionDef/Lambda, so ``jax.jit(step, ...)`` after
    ``def step(...)`` resolves. Subclasses override ``handle_call`` /
    ``handle_functiondef``."""

    def __init__(self):
        self._scopes: List[Dict[str, FuncNode]] = [{}]

    # -- scope machinery ------------------------------------------------
    def lookup(self, name: str) -> Optional[FuncNode]:
        for scope in reversed(self._scopes):
            if name in scope:
                return scope[name]
        return None

    def _visit_scope(self, node):
        self._scopes.append({})
        self.generic_visit(node)
        self._scopes.pop()

    def visit_FunctionDef(self, node):
        self._scopes[-1][node.name] = node
        self.handle_functiondef(node)
        self._visit_scope(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self._visit_scope(node)

    def visit_Assign(self, node):
        # fn = lambda ...: — name the lambda so jit(fn) resolves
        if isinstance(node.value, ast.Lambda) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            self._scopes[-1][node.targets[0].id] = node.value
        self.generic_visit(node)

    def visit_Call(self, node):
        self.handle_call(node)
        self.generic_visit(node)

    # -- hooks ----------------------------------------------------------
    def handle_call(self, node: ast.Call):
        pass

    def handle_functiondef(self, node):
        pass

    def resolve_jit_target(self, call: ast.Call) -> Optional[FuncNode]:
        """First positional arg of a jax.jit call -> the function node it
        names (same-module lexical lookup), or the inline lambda itself."""
        if not call.args:
            return None
        target = call.args[0]
        if isinstance(target, ast.Lambda):
            return target
        if isinstance(target, ast.Name):
            return self.lookup(target.id)
        return None
