"""Rule ``donate-arity``: donate_argnums/static_argnums indices must match
the wrapped function's positional signature.

The motivating bug class: a signature gains a parameter and a hand-counted
``donate_argnums`` tuple silently shifts — XLA then aliases the wrong
buffer (or a scalar) and the cache it was supposed to donate is copied
whole every step. Arity drift is fully decidable from the AST whenever the
jitted function is defined in the same module (the repo's universal
pattern: ``def step(...): ...; return jax.jit(step, donate_argnums=...)``).
"""

import ast

from deepspeed_tpu.analysis.framework import Rule, register
from deepspeed_tpu.analysis.rules._common import (
    ScopeResolver,
    const_argnums,
    func_label,
    is_jax_jit,
    jit_call_kwargs,
    partial_jit_kwargs,
    positional_arity,
)


@register
class DonateArityRule(Rule):
    name = "donate-arity"
    severity = "error"
    description = (
        "donate_argnums/static_argnums must be in-range, duplicate-free, "
        "and non-overlapping for the function handed to jax.jit"
    )

    def check(self, ctx):
        rule = self
        findings = []

        class V(ScopeResolver):
            def handle_call(self, call):
                if is_jax_jit(call.func):
                    kwargs = jit_call_kwargs(call)
                    fn = self.resolve_jit_target(call)
                    findings.extend(_check_site(ctx, rule, call, kwargs, fn))

            def handle_functiondef(self, node):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        kwargs = (
                            jit_call_kwargs(dec) if is_jax_jit(dec.func)
                            else partial_jit_kwargs(dec)
                        )
                        if kwargs is not None:
                            findings.extend(_check_site(ctx, rule, dec, kwargs, node))

        V().visit(ctx.tree)
        return findings


def _check_site(ctx, rule, site, kwargs, fn):
    donate = const_argnums(kwargs.get("donate_argnums"))
    static = const_argnums(kwargs.get("static_argnums"))
    out = []

    for label, nums in (("donate_argnums", donate), ("static_argnums", static)):
        if nums is None:
            continue
        seen = set()
        for i in nums:
            if i in seen:
                out.append(ctx.finding(rule, site, f"{label} lists index {i} twice"))
            seen.add(i)
            if i < 0:
                out.append(ctx.finding(
                    rule, site,
                    f"{label} index {i} is negative — jax resolves argnums "
                    f"positionally; use the explicit position"))
    if donate is not None and static is not None:
        overlap = sorted(set(donate) & set(static))
        for i in overlap:
            out.append(ctx.finding(
                rule, site,
                f"index {i} appears in both donate_argnums and static_argnums "
                f"(jax rejects the intersection at trace time)"))

    if fn is not None:
        n_pos, has_vararg = positional_arity(fn)
        for label, nums in (("donate_argnums", donate), ("static_argnums", static)):
            if nums is None or has_vararg:
                continue
            for i in nums:
                if i >= n_pos:
                    out.append(ctx.finding(
                        rule, site,
                        f"{label} index {i} is out of range for "
                        f"'{func_label(fn)}' which takes {n_pos} positional "
                        f"argument(s)"))
    return out
