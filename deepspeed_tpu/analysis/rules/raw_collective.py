"""Rule ``raw-collective-in-hot-path``: direct ``lax`` collectives in the
wire-bound serving/MoE/pipeline modules.

The quantized-collectives layer (``comm/quantized.py``) is the designated
entry point for the hot wires: it decomposes each collective so only int8
payloads + fp32 block scales cross ICI when ``comm_quant="int8"``, and it
records per-wire byte accounting either way. A raw ``lax.all_to_all``/
``lax.ppermute``/``lax.psum`` added to one of these modules bypasses both
the quantization seam and the accounting — the wire silently goes back to
full width and never shows up in ``/metrics``.

Scope is this rule's OWN hot set (serving/, inference/v2/, parallel/moe/,
runtime/pipe/, and comm/ itself) — not the framework default used by the
host-sync rule, which targets latency (runtime/zero/) rather than wire
width. ``comm/quantized.py`` and ``comm/overlap_tiled.py`` are the
DESIGNATED seam modules: their per-tile ``ppermute`` rings and all-to-all
hops ARE the decomposition every other hot-path collective must route
through, so they are exempt (as are the 1-bit compression seam and the
dist-compat facade, see ``SEAM_MODULES``) — a raw collective in any
*other* comm/ module is a new wire dodging the seams. Sites that are
intentionally raw
(broadcast-from-last-stage psums, the ``comm_quant="none"`` bit-identical
send path) carry ``# dstpu: noqa[raw-collective-in-hot-path]``, which
doubles as documentation of why the wire stays full width.
"""

import ast
import os

from deepspeed_tpu.analysis.framework import Rule, register
from deepspeed_tpu.analysis.rules._common import dotted_name

#: wire-bound subtrees: every collective here should route through
#: comm/quantized.py / comm/overlap_tiled.py (or carry a noqa explaining
#: why it stays raw)
HOT_WIRE_PREFIXES = (
    "serving/", "inference/v2/", "parallel/moe/", "runtime/pipe/", "comm/",
)

#: exempt modules: the seam modules' raw ppermute/all_to_all calls ARE the
#: decomposed transport every hot wire routes through; comm/comm.py is the
#: torch.distributed-compat facade whose wrapper bodies are, by definition,
#: the raw primitives (the rule targets call SITES that bypass the seams,
#: not the layer beneath them)
SEAM_MODULES = (
    "comm/quantized.py",          # int8 wire seam
    "comm/overlap_tiled.py",      # tile-granular overlap seam
    "runtime/comm/compressed.py",  # 1-bit error-feedback compression seam
    "comm/comm.py",               # dist-compat facade (below the seams)
)

_RAW_COLLECTIVES = {
    "lax.all_to_all", "jax.lax.all_to_all",
    "lax.ppermute", "jax.lax.ppermute",
    "lax.psum", "jax.lax.psum",
}


@register
class RawCollectiveInHotPathRule(Rule):
    name = "raw-collective-in-hot-path"
    severity = "warning"
    description = (
        "direct lax.all_to_all/ppermute/psum in a wire-bound module "
        "(serving/MoE/pipeline) bypasses the comm_quant seam and its "
        "wire-byte accounting; route through comm/quantized.py or annotate "
        "the intentionally-raw site"
    )

    def check(self, ctx):
        norm = ctx.path.replace(os.sep, "/")
        if not any(frag in norm for frag in HOT_WIRE_PREFIXES):
            return []
        if any(norm.endswith(seam) for seam in SEAM_MODULES):
            return []
        rule = self
        findings = []

        class V(ast.NodeVisitor):
            def visit_Call(self, node):
                name = dotted_name(node.func)
                if name in _RAW_COLLECTIVES:
                    findings.append(ctx.finding(
                        rule, node,
                        f"raw {name}() on a hot wire: route through "
                        "comm.quantized (quantized_psum_tp/quantized_all_to_all/"
                        "quantized_ppermute honor the comm_quant seam and "
                        "record wire bytes), or mark the site "
                        "# dstpu: noqa[raw-collective-in-hot-path] with why "
                        "it must stay full width",
                    ))
                self.generic_visit(node)

        V().visit(ctx.tree)
        return findings
