"""Rule ``kv-host-bounce``: host materialization of KV payloads in the
cluster handoff hot path.

The whole point of the ``device`` KV transport is that a prefill->decode
handoff never round-trips blocks through host numpy — exported windows
stay resident as jax device arrays and land in the target pool via the
donated scatter. A stray ``np.asarray``/``jax.device_get`` in
``serving/cluster/`` silently reintroduces the PCIe bounce (and the sync)
the transport seam exists to remove, and nothing else would catch it: the
payload still scatters correctly, just ~10x slower per handoff.

The rule fires on every host-copy call in ``serving/cluster/`` and
``serving/net/`` modules, loop or not — ONE bounce per handoff is already
the regression. The net wire moves the HOST representation by design, but
its hot paths must stay zero-copy over that representation:
``np.frombuffer`` decode views and ``tobytes`` of already-host planes are
fine, while an ``np.asarray``/``device_get`` would mean a device sync
snuck into the socket thread. Sites that deliberately touch host data
(token staging, chain hashing over prompt tokens, the host transport
itself) are annotated with ``# dstpu: noqa[kv-host-bounce]``, which
doubles as documentation of why the copy is not a KV payload.
"""

import ast

from deepspeed_tpu.analysis.framework import Rule, register
from deepspeed_tpu.analysis.rules._common import dotted_name

_BOUNCE_CALLS = {
    "jax.device_get", "device_get",
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jnp.asarray",
}

_HOT_FRAGMENTS = ("serving/cluster/", "serving/net/")


@register
class KVHostBounceRule(Rule):
    name = "kv-host-bounce"
    severity = "warning"
    description = (
        "host-copy call (np.asarray/np.array/jax.device_get) in a "
        "serving/cluster/ or serving/net/ module bounces KV payloads "
        "through host memory, defeating the device handoff transport "
        "(or syncing the device inside a socket thread)"
    )

    def check(self, ctx):
        norm = ctx.path.replace("\\", "/")
        if not any(f in norm for f in _HOT_FRAGMENTS):
            return []
        rule = self
        findings = []

        class V(ast.NodeVisitor):
            def visit_Call(self, node):
                name = dotted_name(node.func)
                if name in _BOUNCE_CALLS:
                    findings.append(ctx.finding(
                        rule, node,
                        f"{name}() materializes a host copy on the cluster "
                        "handoff path; keep KV payloads as device arrays "
                        "(device transport) or annotate the deliberate "
                        "host touch with # dstpu: noqa[kv-host-bounce]",
                    ))
                self.generic_visit(node)

        V().visit(ctx.tree)
        return findings
