"""Rule catalog: importing this package registers every rule.

Adding a rule: create a module here with a ``@register``-decorated
``Rule`` subclass and import it below (docs/ANALYSIS.md walks through it).
"""

from deepspeed_tpu.analysis.rules import (  # noqa: F401
    asserts,
    concurrency,
    donation,
    host_sync,
    jit_purity,
    kv_host_bounce,
    lock_discipline,
    raw_collective,
    shard_specs,
    swallowed_errors,
)
