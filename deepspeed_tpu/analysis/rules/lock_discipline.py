"""Lock-discipline rules, backed by the whole-tree lock model.

These four rules consume ``ctx.lock_model`` (``analysis/locks.py``) —
built once per lint run from every parsed file — so they can reason
about lock *ordering* across classes and modules, which no per-file
pass can:

``lock-order-inversion``: a cycle in the cross-module lock-acquisition
graph (thread 1 takes A then B, thread 2 takes B then A) is a potential
deadlock the moment both paths run concurrently. The repo's documented
order is ``EngineCore.step_lock -> Router._cond -> leaf locks``
(docs/ANALYSIS.md "Lock discipline"); this rule proves no code path
closes a cycle against it.

``blocking-call-under-lock``: unbounded blocking (socket accept/recv,
``queue.get()``/``join()``/``wait()`` without timeout, ``time.sleep``,
``block_until_ready``, subprocess spawns) while holding a lock wedges
every thread that needs that lock — the exact shape the serving hang
watchdog (``EngineCore.probe``) exists to recover from at runtime.
``Condition.wait`` on the *held* condition is exempt (the wait releases
it: that is the CV protocol).

``locked-call-to-locking-method``: calling a non-``*_locked`` method
that (transitively) acquires a non-reentrant lock the caller already
holds is a guaranteed self-deadlock. Fix: convert the lock to an
``RLock`` with a comment, or split the callee into a ``*_locked``
helper the lock-holding path calls directly.

``guarded-read-unlocked``: an attribute the model proves is guarded
(written under the class's lock somewhere, or declared via
``# dstpu: guarded-by[attr, lock]``) read outside the lock in a
non-``*_locked`` method sees torn/stale state. Deliberate lock-free
reads (watchdog probes of a possibly-wedged peer) carry a reasoned
``# dstpu: noqa[guarded-read-unlocked]``.
"""

from deepspeed_tpu.analysis.framework import Rule, register


def _held_str(held) -> str:
    return ", ".join(held)


@register
class LockOrderInversionRule(Rule):
    name = "lock-order-inversion"
    severity = "error"
    description = (
        "cycle in the cross-module lock-acquisition graph: two code paths "
        "acquire the same locks in opposite orders (potential deadlock)"
    )

    def check(self, ctx):
        model = ctx.lock_model
        findings = []
        for cycle in model.cycles():
            rendered = " -> ".join(cycle + [cycle[0]])
            edges = list(zip(cycle, cycle[1:] + [cycle[0]]))
            for a, b in edges:
                for site in model.order_edges.get((a, b), ()):
                    if site.path != ctx.path:
                        continue
                    findings.append(ctx.finding(
                        self, site.line,
                        f"acquiring {b} while holding {a} closes the lock "
                        f"cycle {rendered}; another path takes these locks "
                        f"in the opposite order — pick one global order "
                        f"(docs/ANALYSIS.md) and restructure this path"))
        return findings


@register
class BlockingCallUnderLockRule(Rule):
    name = "blocking-call-under-lock"
    severity = "warning"
    description = (
        "unbounded blocking call (socket recv/accept, queue.get/join/wait "
        "without timeout, time.sleep, block_until_ready, subprocess) while "
        "holding a lock wedges every thread needing that lock"
    )

    def check(self, ctx):
        model = ctx.lock_model
        findings = []
        for facts in model.method_facts.values():
            if facts.path != ctx.path:
                continue
            for b in facts.blocking:
                findings.append(ctx.finding(
                    self, b.site.line,
                    f"{b.desc} {b.reason} while holding "
                    f"{_held_str(b.held)}; move the blocking call outside "
                    f"the lock or bound it with a timeout"))
        return findings


@register
class LockedCallToLockingMethodRule(Rule):
    name = "locked-call-to-locking-method"
    severity = "error"
    description = (
        "self-call to a non-*_locked method that re-acquires a held "
        "non-reentrant lock: guaranteed self-deadlock"
    )

    def check(self, ctx):
        model = ctx.lock_model
        findings = []
        for facts in model.method_facts.values():
            if facts.path != ctx.path or facts.cls is None:
                continue
            cm = model.classes.get(facts.cls)
            if cm is None:
                continue
            # direct nested re-acquisition of an own non-reentrant lock:
            # `with self._lock:` inside a block already holding it
            for acq in facts.acquisitions:
                decl = model.lock_decl(acq.lock)
                if (acq.lock in acq.held and decl is not None
                        and decl.cls == facts.cls and not decl.reentrant):
                    findings.append(ctx.finding(
                        self, acq.site.line,
                        f"re-acquiring non-reentrant {acq.lock} already "
                        f"held on this path: self-deadlock; convert to "
                        f"RLock or drop the inner acquisition"))
            # self-calls whose callee (transitively) takes a held lock
            for call in facts.calls:
                if not call.is_self_call or not call.held:
                    continue
                _, callee_name = call.callee
                if callee_name.endswith("_locked"):
                    continue
                for lock in sorted(model.may_acquire(call.callee)):
                    decl = model.lock_decl(lock)
                    if (lock in call.held and decl is not None
                            and not decl.reentrant):
                        findings.append(ctx.finding(
                            self, call.site.line,
                            f"self.{callee_name}() acquires non-reentrant "
                            f"{lock} which this path already holds: "
                            f"self-deadlock; call a *_locked variant or "
                            f"convert the lock to RLock with a comment"))
        return findings


@register
class GuardedReadUnlockedRule(Rule):
    name = "guarded-read-unlocked"
    severity = "warning"
    description = (
        "read of a lock-guarded attribute outside the lock in a "
        "non-*_locked method: torn/stale state under concurrency"
    )

    def check(self, ctx):
        model = ctx.lock_model
        findings = []
        for facts in model.method_facts.values():
            if facts.path != ctx.path or facts.cls is None:
                continue
            if facts.name == "__init__" or facts.locked_contract:
                continue
            cm = model.classes.get(facts.cls)
            if cm is None:
                continue
            # a read that is itself a flagged write site (e.g. the receiver
            # of self.q.append) is unlocked-shared-mutation's finding, not
            # a second one here
            write_sites = {(w.attr, w.site.line) for w in facts.writes}
            seen = set()
            for r in facts.reads:
                guard = cm.guarded.get(r.attr)
                if guard is None:
                    continue
                key = cm.lock_key(guard)
                if key in r.held:
                    continue
                if (r.attr, r.site.line) in write_sites:
                    continue
                if (r.attr, r.site.line) in seen:
                    continue
                seen.add((r.attr, r.site.line))
                findings.append(ctx.finding(
                    self, r.site.line,
                    f"self.{r.attr} is guarded by self.{guard} "
                    f"(written under it elsewhere in {facts.cls}) but read "
                    f"here without the lock; take `with self.{guard}:` or "
                    f"rename the method *_locked if the caller holds it"))
        return findings
