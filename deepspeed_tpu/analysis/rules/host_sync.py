"""Rule ``host-sync-in-loop``: device-sync calls inside Python loops in
latency-critical modules.

Each ``np.asarray``/``.item()``/``float()``/``block_until_ready``/
``device_get`` on a device array blocks the host until the device catches
up; inside a per-step or per-row loop those round-trips serialize the whole
pipeline (the measured failure mode behind engine_v2's one-sync-per-phase
prefill design). The rule fires only in hot modules (serving/,
inference/v2/, runtime/zero/ by default) and only inside ``for``/``while``
bodies — a deliberate, batched transfer point is annotated with
``# dstpu: noqa[host-sync-in-loop]`` which doubles as documentation.
"""

import ast

from deepspeed_tpu.analysis.framework import Rule, register
from deepspeed_tpu.analysis.rules._common import dotted_name

_SYNC_ATTRS = {"block_until_ready", "item"}
_SYNC_CALLS = {
    "jax.device_get", "device_get",
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
}


@register
class HostSyncInLoopRule(Rule):
    name = "host-sync-in-loop"
    severity = "warning"
    description = (
        "host-sync call (block_until_ready/device_get/np.asarray/.item()/"
        "float()) inside a loop in a hot module stalls the device pipeline "
        "once per iteration"
    )

    def check(self, ctx):
        if not ctx.hot_module:
            return []
        rule = self
        findings = []

        class V(ast.NodeVisitor):
            def __init__(self):
                self.loop_depth = 0

            def _loop(self, node):
                self.loop_depth += 1
                self.generic_visit(node)
                self.loop_depth -= 1

            visit_For = visit_AsyncFor = visit_While = _loop

            def visit_FunctionDef(self, node):
                # a def inside a loop body is not executed per-iteration
                saved, self.loop_depth = self.loop_depth, 0
                self.generic_visit(node)
                self.loop_depth = saved

            visit_AsyncFunctionDef = visit_Lambda = visit_FunctionDef

            def visit_Call(self, node):
                if self.loop_depth > 0:
                    msg = _sync_message(node)
                    if msg:
                        findings.append(ctx.finding(rule, node, msg))
                self.generic_visit(node)

        V().visit(ctx.tree)
        return findings


def _sync_message(call: ast.Call):
    if isinstance(call.func, ast.Attribute) and call.func.attr in _SYNC_ATTRS:
        return (f".{call.func.attr}() forces a device sync every iteration; "
                f"hoist it out of the loop or batch the transfer")
    name = dotted_name(call.func)
    if name in _SYNC_CALLS:
        return (f"{name}() on a device value copies to host every iteration; "
                f"hoist it out of the loop or batch the transfer")
    if name == "float" and call.args and not isinstance(call.args[0], ast.Constant):
        return ("float() on a device scalar forces a device sync every "
                "iteration; hoist it out of the loop or batch the transfer")
    return None
