"""Rule ``bare-assert``: ``assert`` as a runtime invariant guard in library
code.

``python -O`` strips every assert, so an invariant guarded by one simply
stops being checked in optimized deployments — the guard must be an
explicit ``raise ValueError/RuntimeError``. Test code is exempt by
convention (pytest assertions are the idiom there); this rule is meant to
run over the package tree only.
"""

import ast

from deepspeed_tpu.analysis.framework import Rule, register


@register
class BareAssertRule(Rule):
    name = "bare-assert"
    severity = "error"
    description = (
        "assert used as a runtime invariant guard vanishes under python -O; "
        "raise ValueError/RuntimeError instead"
    )

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assert):
                yield ctx.finding(
                    self, node,
                    "bare assert guards a runtime invariant but vanishes under "
                    "python -O; raise ValueError/RuntimeError (or suppress with "
                    "# dstpu: noqa[bare-assert] for debug-only checks)")
