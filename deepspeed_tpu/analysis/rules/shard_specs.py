"""Rule ``shard-map-axis-coverage``: every mesh axis a ``shard_map`` call
declares manual (``axis_names``) must be sharded over by at least one
``in_specs``/``out_specs`` entry or used by the body.

The motivating bug class: a mesh config gains an axis (``context``,
``sequence``, ...) and a ``shard_map`` site lists it in ``axis_names``
without threading it into any PartitionSpec — every device along that
axis then holds a full replica and computes identical work, silently
erasing the memory/compute win the axis was configured for. The repo
passes ``check_vma=False`` everywhere (the compat shim's contract), so
jax's own replication checking never sees it; this rule is the static
stand-in.

Resolution is best-effort and conservative: axis names come from string
literals and the canonical ``parallel.topology`` constants (mirrored
below — keep in sync), specs referenced by name resolve through simple
same-file assignments, and a call whose ``axis_names`` or body cannot be
resolved statically is skipped rather than guessed at.
"""

import ast

from deepspeed_tpu.analysis.framework import Rule, register
from deepspeed_tpu.analysis.rules._common import dotted_name

# mirror of deepspeed_tpu/parallel/topology.py — the configured mesh axes
_AXIS_CONSTS = {
    "PIPE_AXIS": "pipe",
    "DATA_AXIS": "data",
    "ZERO_AXIS": "zero",
    "EXPERT_AXIS": "expert",
    "CONTEXT_AXIS": "context",
    "SEQUENCE_AXIS": "sequence",
    "MODEL_AXIS": "model",
}
_AXIS_GROUPS = {
    "MESH_AXES": tuple(_AXIS_CONSTS.values()),
    "BATCH_AXES": ("data", "zero", "expert"),
    "ZERO_AXES": ("data", "zero"),
    "HEAD_AXES": ("model", "sequence"),
}
_KNOWN_AXES = frozenset(_AXIS_CONSTS.values())

_SHARD_MAP_NAMES = (
    "shard_map",
    "jax.shard_map",
    "shard_map.shard_map",
    "jax.experimental.shard_map.shard_map",
)


def _is_shard_map(node: ast.AST) -> bool:
    return dotted_name(node) in _SHARD_MAP_NAMES


def _collect_defs(tree):
    """(assigns, funcs): simple same-file ``name = expr`` assignments (a
    name assigned in several scopes keeps every value — mention-finding
    only needs ONE of them to carry the axis) and function definitions."""
    assigns, funcs = {}, {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            assigns.setdefault(node.targets[0].id, []).append(node.value)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.setdefault(node.name, node)
    return assigns, funcs


def _axes_in(node, assigns, seen=None):
    """Every configured mesh axis mentioned anywhere under ``node``:
    string literals, topology constants/groups by name, and names that
    resolve through one or more same-file assignments."""
    if node is None:
        return set()
    seen = set() if seen is None else seen
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and sub.value in _KNOWN_AXES:
            out.add(sub.value)
        elif isinstance(sub, ast.Name):
            if sub.id in _AXIS_CONSTS:
                out.add(_AXIS_CONSTS[sub.id])
            elif sub.id in _AXIS_GROUPS:
                out.update(_AXIS_GROUPS[sub.id])
            elif sub.id in assigns and sub.id not in seen:
                seen.add(sub.id)
                for value in assigns[sub.id]:
                    out.update(_axes_in(value, assigns, seen))
    return out


def _manual_axes(expr):
    """The ``axis_names`` value -> set of axis strings, or None when it is
    not a statically-resolvable literal (``set(topo.mesh.axis_names)``,
    computed sets, ...)."""
    if isinstance(expr, ast.Call):
        # set(GROUP_CONST) — the full-tuple spelling
        if dotted_name(expr.func) == "set" and len(expr.args) == 1 and \
                isinstance(expr.args[0], ast.Name) and \
                expr.args[0].id in _AXIS_GROUPS:
            return set(_AXIS_GROUPS[expr.args[0].id])
        return None
    if not isinstance(expr, (ast.Set, ast.Tuple, ast.List)):
        return None
    out = set()
    for elt in expr.elts:
        if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
            out.add(elt.value)
        elif isinstance(elt, ast.Name) and elt.id in _AXIS_CONSTS:
            out.add(_AXIS_CONSTS[elt.id])
        elif isinstance(elt, ast.Starred) and isinstance(elt.value, ast.Name) \
                and elt.value.id in _AXIS_GROUPS:
            out.update(_AXIS_GROUPS[elt.value.id])
        else:
            return None
    return out


@register
class ShardMapAxisCoverageRule(Rule):
    name = "shard-map-axis-coverage"
    severity = "warning"
    description = (
        "a mesh axis declared manual via shard_map axis_names must appear "
        "in some in_specs/out_specs entry (or be used by the body) — an "
        "omitted axis silently replicates the whole computation"
    )

    def check(self, ctx):
        assigns, funcs = _collect_defs(ctx.tree)
        findings = []
        for call in ast.walk(ctx.tree):
            if not (isinstance(call, ast.Call) and _is_shard_map(call.func)):
                continue
            kwargs = {kw.arg: kw.value for kw in call.keywords if kw.arg}
            manual = _manual_axes(kwargs.get("axis_names"))
            if not manual:
                continue  # absent or not statically resolvable
            if "in_specs" not in kwargs and "out_specs" not in kwargs:
                continue
            body = call.args[0] if call.args else None
            if isinstance(body, ast.Name):
                body = funcs.get(body.id)
            if body is None or not isinstance(
                    body, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # body defined elsewhere — cannot prove anything
            covered = (
                _axes_in(kwargs.get("in_specs"), assigns)
                | _axes_in(kwargs.get("out_specs"), assigns)
                | _axes_in(body, assigns)
            )
            for ax in sorted(manual - covered):
                findings.append(ctx.finding(
                    self, call,
                    f"axis_names declares mesh axis '{ax}' manual but no "
                    f"in_specs/out_specs entry shards over it and the body "
                    f"never references it — every device along '{ax}' "
                    f"computes a full replica"))
        return findings
