"""Rule ``swallowed-thread-exception``: broad except handlers inside
serving thread loops that only log (or pass) and carry on.

A serving worker/controller thread runs a ``while True`` loop; an
``except Exception`` in that loop whose handler merely logs and
continues turns a dead replica into a live-looking corpse — it keeps
taking placements while serving nothing (the failure mode the
resilience layer's ``Router._worker_failed`` exists to prevent). A
handler in a thread loop must DO something with the failure: mark the
replica's health, fail or recover the affected requests, append a
control-plane event, or re-raise. Handlers that only call ``logger.*``
/ ``logging.*`` / ``print`` / ``time.sleep`` (plus bare ``pass`` /
``continue``) are flagged. A loop that genuinely wants log-and-continue
semantics (e.g. an idempotent retry of a pure side-effect) documents it
with ``# dstpu: noqa[swallowed-thread-exception]`` on the handler line.
"""

import ast

from deepspeed_tpu.analysis.framework import Rule, register
from deepspeed_tpu.analysis.rules._common import dotted_name

#: call prefixes that count as "only telling a human", not handling
_LOG_PREFIXES = ("logger.", "logging.", "log.", "warnings.")
_LOG_BARE = {"print"}
_SLEEP_CALLS = {"time.sleep", "sleep"}


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    """Bare ``except:`` or ``except Exception`` / ``BaseException``."""
    if handler.type is None:
        return True
    name = dotted_name(handler.type)
    return name in ("Exception", "BaseException")


def _swallow_only(body) -> bool:
    """True when every statement is logging/pass/continue/sleep — nothing
    that could mark health, fail a request, or surface the error."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            name = dotted_name(stmt.value.func) or ""
            if (name in _LOG_BARE or name in _SLEEP_CALLS
                    or name.startswith(_LOG_PREFIXES)):
                continue
            # self.logger.warning(...) and friends
            if any(seg in ("logger", "logging") for seg in name.split(".")):
                continue
        return False
    return True


@register
class SwallowedThreadExceptionRule(Rule):
    name = "swallowed-thread-exception"
    severity = "warning"
    description = (
        "broad except inside a serving thread loop that only logs and "
        "continues — the failure never reaches health tracking or the "
        "affected requests, leaving a dead replica looking alive"
    )

    def check(self, ctx):
        if "serving/" not in ctx.path.replace("\\", "/"):
            return []
        rule = self
        findings = []

        class V(ast.NodeVisitor):
            def __init__(self):
                self.while_depth = 0

            def visit_While(self, node):
                self.while_depth += 1
                self.generic_visit(node)
                self.while_depth -= 1

            def visit_FunctionDef(self, node):
                # a def inside the loop body runs in its caller's context,
                # not per-iteration of THIS loop
                saved, self.while_depth = self.while_depth, 0
                self.generic_visit(node)
                self.while_depth = saved

            visit_AsyncFunctionDef = visit_Lambda = visit_FunctionDef

            def visit_Try(self, node):
                if self.while_depth > 0:
                    for handler in node.handlers:
                        if (_is_broad_handler(handler)
                                and _swallow_only(handler.body)):
                            findings.append(ctx.finding(
                                rule, handler,
                                "broad except in a thread loop swallows the "
                                "failure (handler only logs/sleeps); mark "
                                "replica health, fail/recover the requests, "
                                "or re-raise"))
                self.generic_visit(node)

        V().visit(ctx.tree)
        return findings
