"""Concurrency rules for driver-style threaded code.

``cond-wait-no-predicate``: ``Condition.wait()`` outside a ``while`` loop.
Condition variables wake spuriously and on every ``notify_all``; a wait
that is not re-checked in a predicate loop acts on stale state. (The
serving driver's ``self._cond.wait(timeout)`` inside its ``while True``
re-check loop is the canonical correct shape.)

``unlocked-shared-mutation``: an attribute that is written under
``with self._lock:`` somewhere in a class is shared state; writing it from
another method WITHOUT the lock is a race. Methods named ``*_locked`` are
exempt by convention (they document being called with the lock held), as
is ``__init__`` (no concurrent access before construction completes).
"""

import ast
import re

from deepspeed_tpu.analysis.framework import Rule, register
from deepspeed_tpu.analysis.rules._common import dotted_name

_COND_NAME = re.compile(r"(cond|condition|cv)$", re.IGNORECASE)
_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}


def _receiver_name(func: ast.AST):
    """'x' for x.wait, '_cond' for self._cond.wait."""
    if isinstance(func, ast.Attribute) and isinstance(func.value, (ast.Attribute, ast.Name)):
        v = func.value
        return v.attr if isinstance(v, ast.Attribute) else v.id
    return None


@register
class CondWaitNoPredicateRule(Rule):
    name = "cond-wait-no-predicate"
    severity = "warning"
    description = (
        "Condition.wait() must sit inside a while loop that re-checks its "
        "predicate (spurious wakeups, notify_all broadcast)"
    )

    def check(self, ctx):
        rule = self
        findings = []

        class V(ast.NodeVisitor):
            def __init__(self):
                self.while_depth = 0

            def visit_While(self, node):
                self.while_depth += 1
                self.generic_visit(node)
                self.while_depth -= 1

            def visit_FunctionDef(self, node):
                saved, self.while_depth = self.while_depth, 0
                self.generic_visit(node)
                self.while_depth = saved

            visit_AsyncFunctionDef = visit_Lambda = visit_FunctionDef

            def visit_Call(self, node):
                func = node.func
                if (isinstance(func, ast.Attribute) and func.attr in ("wait", "wait_for")):
                    recv = _receiver_name(func)
                    if recv and _COND_NAME.search(recv):
                        # wait_for runs its own predicate loop internally
                        if func.attr == "wait" and self.while_depth == 0:
                            findings.append(ctx.finding(
                                rule, node,
                                f"{recv}.wait() outside a while predicate "
                                f"loop acts on spurious/stale wakeups; wrap "
                                f"in `while not <predicate>:` or use "
                                f"wait_for()"))
                self.generic_visit(node)

        V().visit(ctx.tree)
        return findings


@register
class UnlockedSharedMutationRule(Rule):
    name = "unlocked-shared-mutation"
    severity = "warning"
    description = (
        "attribute written under `with self.<lock>:` elsewhere in the class "
        "is mutated here without the lock"
    )

    def check(self, ctx):
        findings = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(ctx, node))
        return findings

    # -- per class ------------------------------------------------------
    def _check_class(self, ctx, cls: ast.ClassDef):
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        lock_attrs = set()
        for m in methods:
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    if dotted_name(node.value.func) in _LOCK_FACTORIES:
                        for t in node.targets:
                            if self._self_attr(t):
                                lock_attrs.add(t.attr)
        if not lock_attrs:
            return []

        writes = []  # (method, attr, node, under_lock)
        for m in methods:
            self._collect_writes(m, m.body, lock_attrs, under=False, out=writes)

        guarded = {attr for (_m, attr, _n, locked) in writes if locked}
        guarded -= lock_attrs
        out = []
        for m, attr, node, locked in writes:
            if locked or attr not in guarded:
                continue
            if m.name == "__init__" or m.name.endswith("_locked"):
                continue
            out.append(ctx.finding(
                self, node,
                f"self.{attr} is written under the lock elsewhere in "
                f"{cls.name} but mutated here without it; move this write "
                f"under `with self.{sorted(lock_attrs)[0]}:` (or rename the "
                f"method *_locked if the caller holds it)"))
        return out

    @staticmethod
    def _self_attr(node):
        return (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name) and node.value.id == "self")

    def _collect_writes(self, method, body, lock_attrs, under, out):
        for node in body:
            locked_here = under
            if isinstance(node, ast.With):
                held = any(
                    self._self_attr(item.context_expr) and item.context_expr.attr in lock_attrs
                    for item in node.items
                )
                self._collect_writes(method, node.body, lock_attrs,
                                     under or held, out)
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if self._self_attr(t):
                        out.append((method, t.attr, node, locked_here))
            elif isinstance(node, ast.AugAssign) and self._self_attr(node.target):
                out.append((method, node.target.attr, node, locked_here))
            # recurse into compound statements, but not nested defs
            for child_body in _sub_bodies(node):
                self._collect_writes(method, child_body, lock_attrs, locked_here, out)


def _sub_bodies(node):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
        return []
    bodies = []
    for field in ("body", "orelse", "finalbody"):
        b = getattr(node, field, None)
        if b:
            bodies.append(b)
    for h in getattr(node, "handlers", []) or []:
        bodies.append(h.body)
    return bodies
