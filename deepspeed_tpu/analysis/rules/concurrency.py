"""Concurrency rules for driver-style threaded code.

``cond-wait-no-predicate``: ``Condition.wait()`` outside a ``while`` loop.
Condition variables wake spuriously and on every ``notify_all``; a wait
that is not re-checked in a predicate loop acts on stale state. (The
serving driver's ``self._cond.wait(timeout)`` inside its ``while True``
re-check loop is the canonical correct shape.)

``unlocked-shared-mutation``: an attribute that is written under
``with self._lock:`` somewhere in a class is shared state; writing it from
another method WITHOUT the lock is a race. Methods named ``*_locked`` are
exempt by convention (they document being called with the lock held), as
is ``__init__`` (no concurrent access before construction completes).
Backed by the whole-tree lock model (``analysis/locks.py``), so augmented
assignment (``self.n += 1``), subscript stores (``self.d[k] = v``) and
in-place mutator calls (``self.q.append(x)``) all count as writes, and
``# dstpu: guarded-by[attr, lock]`` declarations are honored.
"""

import ast
import re

from deepspeed_tpu.analysis.framework import Rule, register

_COND_NAME = re.compile(r"(cond|condition|cv)$", re.IGNORECASE)


def _receiver_name(func: ast.AST):
    """'x' for x.wait, '_cond' for self._cond.wait."""
    if isinstance(func, ast.Attribute) and isinstance(func.value, (ast.Attribute, ast.Name)):
        v = func.value
        return v.attr if isinstance(v, ast.Attribute) else v.id
    return None


@register
class CondWaitNoPredicateRule(Rule):
    name = "cond-wait-no-predicate"
    severity = "warning"
    description = (
        "Condition.wait() must sit inside a while loop that re-checks its "
        "predicate (spurious wakeups, notify_all broadcast)"
    )

    def check(self, ctx):
        rule = self
        findings = []

        class V(ast.NodeVisitor):
            def __init__(self):
                self.while_depth = 0

            def visit_While(self, node):
                self.while_depth += 1
                self.generic_visit(node)
                self.while_depth -= 1

            def visit_FunctionDef(self, node):
                saved, self.while_depth = self.while_depth, 0
                self.generic_visit(node)
                self.while_depth = saved

            visit_AsyncFunctionDef = visit_Lambda = visit_FunctionDef

            def visit_Call(self, node):
                func = node.func
                if (isinstance(func, ast.Attribute) and func.attr in ("wait", "wait_for")):
                    recv = _receiver_name(func)
                    if recv and _COND_NAME.search(recv):
                        # wait_for runs its own predicate loop internally
                        if func.attr == "wait" and self.while_depth == 0:
                            findings.append(ctx.finding(
                                rule, node,
                                f"{recv}.wait() outside a while predicate "
                                f"loop acts on spurious/stale wakeups; wrap "
                                f"in `while not <predicate>:` or use "
                                f"wait_for()"))
                self.generic_visit(node)

        V().visit(ctx.tree)
        return findings


_WRITE_VERB = {
    "assign": "written",
    "augassign": "updated in place",
    "subscript": "mutated by subscript store",
    "mutator": "mutated in place",
}


@register
class UnlockedSharedMutationRule(Rule):
    name = "unlocked-shared-mutation"
    severity = "warning"
    description = (
        "attribute written under `with self.<lock>:` elsewhere in the class "
        "is mutated here without the lock (plain/augmented assignment, "
        "subscript store, or in-place mutator call)"
    )

    def check(self, ctx):
        model = ctx.lock_model
        findings = []
        for cm in model.classes.values():
            if cm.path != ctx.path or not cm.locks:
                continue
            for (cls, mname), facts in model.method_facts.items():
                if cls != cm.name:
                    continue
                if mname == "__init__" or mname.endswith("_locked"):
                    continue
                for w in facts.writes:
                    guard = cm.guarded.get(w.attr)
                    if guard is None or cm.lock_key(guard) in w.held:
                        continue
                    verb = _WRITE_VERB.get(w.kind, "written")
                    findings.append(ctx.finding(
                        self, w.site.line,
                        f"self.{w.attr} is written under the lock elsewhere "
                        f"in {cm.name} but {verb} here without it; move "
                        f"this write under `with self.{guard}:` (or rename "
                        f"the method *_locked if the caller holds it)"))
        return findings
