"""Whole-tree lock model for the lock-discipline rules (Tier A).

``dstpu lint``'s concurrency rules used to reason one class at a time.
This module builds a model of the *entire lint run* — every file parsed
once, cross-referenced — so rules can answer questions no per-file pass
can:

* **lock registry** — which classes own locks (``self._lock =
  threading.Lock()``), which modules own global locks
  (``_BUILD_LOCK = threading.Lock()``), and each lock's kind
  (``Lock`` / ``RLock`` / ``Condition``; only ``RLock`` is reentrant).
* **guarded attributes** — an attribute written under ``with
  self._lock:`` anywhere in a class is shared state *everywhere* in that
  class. Augmented assignment (``self.n += 1``), subscript stores
  (``self.d[k] = v``) and in-place mutator calls (``self.q.append(x)``)
  all count as writes. Explicit contracts come from
  ``# dstpu: guarded-by[attr, lock]`` comments inside the class body,
  and ``*_locked``-suffixed methods declare "caller holds the lock".
* **acquisition graph** — who acquires what while holding what,
  following ``self.x.method()`` calls across classes through inferred
  attribute/parameter types (``Router._cond`` sites that call
  ``self.metrics.inc`` add the edge ``Router._cond ->
  ServingMetrics._lock``). Cycles in this graph are potential
  deadlocks; ``analysis/lockwitness.py`` checks the *observed* runtime
  graph against this static one.

The model is pure AST — no imports of the analyzed code, no execution —
so it runs anywhere the linter runs. It is deliberately unsound in the
usual static-analysis ways (unresolvable receivers are skipped, not
guessed), trading false negatives for a near-zero false-positive rate:
every edge it reports comes with a concrete ``path:line`` witness.
"""

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "LockDecl",
    "LockModel",
    "ClassModel",
    "Site",
    "build_model",
    "build_model_from_paths",
]

#: constructor callees that create a lock object
_LOCK_FACTORIES = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "Lock": "Lock",
    "RLock": "RLock",
    "Condition": "Condition",
}

#: method names that mutate their receiver in place (list/dict/set/deque)
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "clear", "update", "add", "discard", "setdefault",
    "rotate", "sort", "reverse",
}

#: explicit guarded-by contract: ``# dstpu: guarded-by[attr, lock]``
_GUARDED_BY_RE = re.compile(
    r"#\s*dstpu:\s*guarded-by\[\s*([A-Za-z_]\w*)\s*,\s*([A-Za-z_]\w*)\s*\]")

#: explicit return-type contract on a factory function whose annotation
#: can't name one class (e.g. returns the null OR the real injector):
#: ``def get_fault_injector():  # dstpu: returns[FaultInjector]``
_RETURNS_RE = re.compile(r"#\s*dstpu:\s*returns\[\s*([A-Za-z_]\w*)\s*\]")

#: calls that can block indefinitely; value = human reason
_BLOCKING_CALLS = {
    "time.sleep": "sleeps while holding the lock",
    "subprocess.run": "spawns a subprocess while holding the lock",
    "subprocess.call": "spawns a subprocess while holding the lock",
    "subprocess.check_call": "spawns a subprocess while holding the lock",
    "subprocess.check_output": "spawns a subprocess while holding the lock",
    "subprocess.Popen": "spawns a subprocess while holding the lock",
}

#: method names that block on I/O or synchronization when called with no
#: timeout argument (socket accept/recv, queue.get, thread/condition waits)
_BLOCKING_METHODS = {
    "accept": "blocks on socket accept",
    "recv": "blocks on socket recv",
    "recv_into": "blocks on socket recv",
    "recvfrom": "blocks on socket recv",
    "connect": "blocks on socket connect",
    "block_until_ready": "synchronizes host with device",
    "get": "blocks on queue.get",
    "join": "blocks joining a thread",
    "wait": "blocks waiting",
    "wait_for": "blocks waiting",
}

#: ``.get``/``.join``/``.wait`` receivers must look synchronization-ish to
#: count (plain dict ``.get(k)`` is not blocking)
_BLOCKING_RECV_HINTS = re.compile(
    r"(queue|q|thread|proc|process|pump|worker|event|evt|barrier|cond|"
    r"condition|cv|done|ready|stop|listener)s?$",
    re.IGNORECASE,
)


# ---------------------------------------------------------------------------
# model dataclasses
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class Site:
    path: str
    line: int

    def render(self) -> str:
        return f"{self.path}:{self.line}"


@dataclass
class LockDecl:
    key: str            # "EngineCore.step_lock" or "op_builder._BUILD_LOCK"
    kind: str           # "Lock" | "RLock" | "Condition" | "Condition(Lock)"
    cls: Optional[str]  # owning class name, None for module-level locks
    attr: str           # attribute / global name
    site: Site = field(default=None)  # type: ignore[assignment]

    @property
    def reentrant(self) -> bool:
        # Condition()'s default lock is an RLock; only an explicit plain
        # Lock argument (kind "Condition(Lock)") makes it non-reentrant
        return self.kind in ("RLock", "Condition")


@dataclass
class _TypeRef:
    """A best-effort static type: a known class name, optionally wrapped
    in one container layer (list/dict-values/deque), in which case
    iterating or subscripting yields the element class."""
    cls: str
    container: bool = False


@dataclass
class _CallFact:
    callee: Tuple[Optional[str], str]  # (class name | None, func/method name)
    site: Site
    held: Tuple[str, ...]
    is_self_call: bool
    recv: str  # rendered receiver, for messages


@dataclass
class _AcqFact:
    lock: str
    site: Site
    held: Tuple[str, ...]
    timeout: bool = False  # acquire(timeout=...) — bounded, not a deadlock


@dataclass
class _BlockFact:
    site: Site
    held: Tuple[str, ...]
    desc: str
    reason: str


@dataclass
class _AccessFact:
    attr: str
    site: Site
    held: Tuple[str, ...]
    kind: str  # "read" | "assign" | "augassign" | "subscript" | "mutator"


@dataclass
class _MethodFacts:
    cls: Optional[str]
    name: str
    path: str
    acquisitions: List[_AcqFact] = field(default_factory=list)
    calls: List[_CallFact] = field(default_factory=list)
    blocking: List[_BlockFact] = field(default_factory=list)
    reads: List[_AccessFact] = field(default_factory=list)
    writes: List[_AccessFact] = field(default_factory=list)

    @property
    def locked_contract(self) -> bool:
        return self.name.endswith("_locked")


@dataclass
class ClassModel:
    name: str
    path: str
    node: ast.ClassDef
    locks: Dict[str, LockDecl] = field(default_factory=dict)
    #: guarded attribute -> guarding lock attr (inferred + declared)
    guarded: Dict[str, str] = field(default_factory=dict)
    #: attr -> best-effort type
    attr_types: Dict[str, _TypeRef] = field(default_factory=dict)
    methods: Dict[str, ast.AST] = field(default_factory=dict)

    def lock_key(self, attr: str) -> str:
        return f"{self.name}.{attr}"


@dataclass
class _FuncInfo:
    name: str
    path: str
    module: str
    node: ast.AST
    returns: Optional[str] = None  # annotated return class name


class LockModel:
    """The whole-tree model. Build with :func:`build_model`; rules consume
    the derived fact lists (each entry carries a ``Site`` so per-file rules
    can filter on ``ctx.path``)."""

    def __init__(self):
        self.classes: Dict[str, ClassModel] = {}
        self.module_locks: Dict[str, LockDecl] = {}
        self.functions: Dict[str, _FuncInfo] = {}
        #: module stem -> {global name: _TypeRef}
        self.module_globals: Dict[str, Dict[str, _TypeRef]] = {}
        self.method_facts: Dict[Tuple[Optional[str], str], _MethodFacts] = {}
        #: (held, acquired) -> witness sites
        self.order_edges: Dict[Tuple[str, str], List[Site]] = {}
        #: non-reentrant lock re-acquired while held: (lock, site, via)
        self.reentrant_hazards: List[Tuple[str, Site, str]] = []
        #: RLock/any lock observed acquired reentrantly (info for audits)
        self.reentrant_acquires: List[Tuple[str, Site, str]] = []
        self._may_acquire_memo: Dict[Tuple[Optional[str], str], Set[str]] = {}

    # -- lock registry ----------------------------------------------------
    def all_locks(self) -> Dict[str, LockDecl]:
        out = dict(self.module_locks)
        for cm in self.classes.values():
            for decl in cm.locks.values():
                out[decl.key] = decl
        return out

    def lock_decl(self, key: str) -> Optional[LockDecl]:
        return self.all_locks().get(key)

    # -- acquisition graph ------------------------------------------------
    def add_edge(self, held: str, acquired: str, site: Site):
        if held == acquired:
            return
        self.order_edges.setdefault((held, acquired), []).append(site)

    def edge_closure(self) -> Set[Tuple[str, str]]:
        """Transitive closure of the static order edges — the contract the
        runtime witness checks observed acquisitions against."""
        adj: Dict[str, Set[str]] = {}
        for a, b in self.order_edges:
            adj.setdefault(a, set()).add(b)
        closure: Set[Tuple[str, str]] = set()
        for start in adj:
            seen: Set[str] = set()
            stack = list(adj[start])
            while stack:
                n = stack.pop()
                if n in seen:
                    continue
                seen.add(n)
                closure.add((start, n))
                stack.extend(adj.get(n, ()))
        return closure

    def cycles(self) -> List[List[str]]:
        """Elementary cycles in the acquisition graph (each a lock-order
        inversion). Returned as node lists without the closing repeat,
        deduplicated by rotation."""
        adj: Dict[str, List[str]] = {}
        for a, b in sorted(self.order_edges):
            adj.setdefault(a, []).append(b)
        cycles: List[List[str]] = []
        seen_keys: Set[Tuple[str, ...]] = set()

        def dfs(start: str, node: str, trail: List[str], visiting: Set[str]):
            for nxt in adj.get(node, ()):
                if nxt == start:
                    cyc = trail[:]
                    i = cyc.index(min(cyc))
                    key = tuple(cyc[i:] + cyc[:i])
                    if key not in seen_keys:
                        seen_keys.add(key)
                        cycles.append(cyc)
                elif nxt not in visiting and nxt > start:
                    # only explore nodes > start: each cycle found once,
                    # rooted at its smallest node
                    visiting.add(nxt)
                    dfs(start, nxt, trail + [nxt], visiting)
                    visiting.discard(nxt)

        for start in sorted(adj):
            dfs(start, start, [start], {start})
        return cycles

    # -- interprocedural summaries -----------------------------------------
    def may_acquire(self, key: Tuple[Optional[str], str],
                    _depth: int = 0) -> Set[str]:
        """Locks a method/function may acquire, transitively through
        resolved calls (memoized, cycle-safe, depth-capped)."""
        if key in self._may_acquire_memo:
            return self._may_acquire_memo[key]
        if _depth > 12:
            return set()
        self._may_acquire_memo[key] = set()  # cycle guard
        facts = self.method_facts.get(key)
        if facts is None:
            return set()
        out: Set[str] = set()
        for acq in facts.acquisitions:
            if not acq.timeout:
                out.add(acq.lock)
        for call in facts.calls:
            out |= self.may_acquire(call.callee, _depth + 1)
        self._may_acquire_memo[key] = out
        return out

    # -- JSON export --------------------------------------------------------
    def to_doc(self) -> dict:
        """The ``model`` section of ``render_json``: locks, guarded attrs,
        acquisition edges (each edge with one witness site)."""
        locks = [
            {"key": d.key, "kind": d.kind, "class": d.cls, "attr": d.attr,
             "path": d.site.path if d.site else None,
             "line": d.site.line if d.site else None}
            for d in sorted(self.all_locks().values(), key=lambda d: d.key)
        ]
        guarded = {
            cm.name: {attr: cm.lock_key(lock)
                      for attr, lock in sorted(cm.guarded.items())}
            for cm in sorted(self.classes.values(), key=lambda c: c.name)
            if cm.guarded
        }
        edges = [
            {"held": a, "acquires": b,
             "site": sites[0].render(), "sites": len(sites)}
            for (a, b), sites in sorted(self.order_edges.items())
        ]
        return {"locks": locks, "guarded": guarded, "edges": edges}


# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------
def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_self_attr(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self")


def _annotation_class(node: Optional[ast.AST]) -> Optional[Tuple[str, bool]]:
    """``EngineCore`` / ``Optional[EngineCore]`` / ``"EngineCore"`` ->
    (name, container=False); ``List[EngineCore]`` / ``Dict[int, EngineCore]``
    / ``Sequence[...]`` -> (elem name, container=True). None otherwise."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(node, ast.Name):
        return node.id, False
    if isinstance(node, ast.Attribute):
        return node.attr, False
    if isinstance(node, ast.Subscript):
        base = _dotted(node.value) or ""
        base = base.split(".")[-1]
        inner = node.slice
        if base == "Optional":
            return _annotation_class(inner)
        if base in ("List", "list", "Sequence", "Set", "set",
                    "FrozenSet", "Tuple", "tuple", "Deque", "deque",
                    "Iterable", "Iterator"):
            got = _annotation_class(inner)
            if got:
                return got[0], True
        if base in ("Dict", "dict", "Mapping", "MutableMapping",
                    "DefaultDict", "OrderedDict"):
            if isinstance(inner, ast.Tuple) and len(inner.elts) == 2:
                got = _annotation_class(inner.elts[1])
                if got:
                    return got[0], True
    return None


def _stmt_exprs(stmt: ast.stmt) -> List[ast.expr]:
    """Expressions hanging directly off a statement (not the nested
    statement bodies — those are walked with their own held-lock state)."""
    out: List[ast.expr] = []
    for name, value in ast.iter_fields(stmt):
        if name in ("body", "orelse", "finalbody", "handlers", "items"):
            continue
        if isinstance(value, ast.expr):
            out.append(value)
        elif isinstance(value, list):
            out.extend(v for v in value if isinstance(v, ast.expr))
    return out


def _sub_bodies(stmt: ast.stmt) -> List[List[ast.stmt]]:
    bodies = []
    for f in ("body", "orelse", "finalbody"):
        b = getattr(stmt, f, None)
        if b:
            bodies.append(b)
    for h in getattr(stmt, "handlers", []) or []:
        bodies.append(h.body)
    return bodies


def _call_timeout_bounded(call: ast.Call) -> bool:
    """True when the call passes a timeout (kwarg or any positional arg on
    wait/get/join/acquire-style calls)."""
    for kw in call.keywords:
        if kw.arg == "timeout":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in (
            "wait", "get", "join", "acquire") and call.args:
        return True
    return False


# ---------------------------------------------------------------------------
# builder
# ---------------------------------------------------------------------------
def build_model(files: Iterable[Tuple[str, str, ast.AST]]) -> LockModel:
    """Build the model from ``(path, text, tree)`` triples (the lint run's
    parsed files)."""
    model = LockModel()
    files = list(files)

    # pass 1: registry — classes, module functions/locks/globals
    for path, text, tree in files:
        _collect_registry(model, path, text, tree)
    # pass 2: per-class attribute types + lock attrs + declared contracts
    for path, text, tree in files:
        _collect_class_details(model, path, text, tree)
    # pass 3: per-method facts (held-lock walk) + guarded inference
    for path, text, tree in files:
        _collect_method_facts(model, path, tree)
    _infer_guarded(model)
    # pass 4: derive the acquisition graph from facts + call summaries
    _derive_edges(model)
    return model


def build_model_from_paths(paths: Sequence[str]) -> LockModel:
    """Convenience: parse ``paths`` (files or directories) and build."""
    from deepspeed_tpu.analysis.framework import iter_py_files
    triples = []
    for p in iter_py_files(paths):
        try:
            with open(p, "r", encoding="utf-8") as f:
                text = f.read()
            triples.append((p, text, ast.parse(text, filename=p)))
        except (OSError, SyntaxError):
            continue
    return build_model(triples)


def _module_stem(path: str) -> str:
    return os.path.splitext(os.path.basename(path))[0]


def _collect_registry(model: LockModel, path: str, text: str, tree: ast.AST):
    stem = _module_stem(path)
    lines = text.splitlines()
    globals_ = model.module_globals.setdefault(stem, {})
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            ret = _annotation_class(node.returns)
            returns = ret[0] if ret and not ret[1] else None
            if returns is None:
                # `# dstpu: returns[Class]` on the def line stands in for
                # an annotation the type system can't express cleanly
                for i in range(node.lineno - 1,
                               min(node.body[0].lineno, len(lines))):
                    m = _RETURNS_RE.search(lines[i])
                    if m:
                        returns = m.group(1)
                        break
            model.functions.setdefault(node.name, _FuncInfo(
                name=node.name, path=path, module=stem, node=node,
                returns=returns))
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = _dotted(node.value.func)
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                if callee in _LOCK_FACTORIES:
                    key = f"{stem}.{t.id}"
                    model.module_locks[key] = LockDecl(
                        key=key, kind=_lock_kind(node.value, callee),
                        cls=None, attr=t.id, site=Site(path, node.lineno))
                elif callee:
                    globals_[t.id] = _TypeRef(callee.split(".")[-1])
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            prev = model.classes.get(node.name)
            cm = ClassModel(name=node.name, path=path, node=node)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    cm.methods[item.name] = item
            # name collisions across modules: prefer the definition that
            # owns locks (resolved in pass 2); for now first-seen wins and
            # pass 2 may replace it
            if prev is None:
                model.classes[node.name] = cm
            else:
                prev_has = _defines_lock(prev.node)
                if not prev_has and _defines_lock(node):
                    model.classes[node.name] = cm


def _lock_kind(call: ast.Call, callee: str) -> str:
    """Resolve the lock kind, distinguishing ``Condition(Lock())`` (whose
    lock is NOT reentrant) from the default ``Condition()`` (RLock)."""
    kind = _LOCK_FACTORIES[callee]
    if kind == "Condition" and call.args:
        arg = call.args[0]
        if isinstance(arg, ast.Call) and \
                _LOCK_FACTORIES.get(_dotted(arg.func)) == "Lock":
            return "Condition(Lock)"
    return kind


def _defines_lock(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _dotted(node.value.func) in _LOCK_FACTORIES:
                return True
    return False


def _collect_class_details(model: LockModel, path: str, text: str,
                           tree: ast.AST):
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        cm = model.classes.get(node.name)
        if cm is None or cm.path != path or cm.node is not node:
            continue
        _collect_locks_and_types(model, cm)
        _collect_guarded_decls(cm, text)


def _collect_locks_and_types(model: LockModel, cm: ClassModel):
    # dataclass-style class-body annotations: `stream: Optional[TokenStream]`
    for item in cm.node.body:
        if isinstance(item, ast.AnnAssign) and isinstance(item.target, ast.Name):
            got = _annotation_class(item.annotation)
            if got:
                cm.attr_types.setdefault(item.target.id, _TypeRef(got[0], got[1]))
    for meth in cm.methods.values():
        # parameter annotations feed self.attr = param inference
        params: Dict[str, _TypeRef] = {}
        args = meth.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            got = _annotation_class(a.annotation)
            if got:
                params[a.arg] = _TypeRef(got[0], got[1])
        for node in ast.walk(meth):
            if isinstance(node, ast.AnnAssign) and _is_self_attr(node.target):
                got = _annotation_class(node.annotation)
                if got:
                    cm.attr_types.setdefault(
                        node.target.attr, _TypeRef(got[0], got[1]))
            if not isinstance(node, ast.Assign):
                continue
            tref = _infer_value_type(model, cm, node.value, params)
            for t in node.targets:
                if not _is_self_attr(t):
                    continue
                if isinstance(node.value, ast.Call):
                    callee = _dotted(node.value.func)
                    if callee in _LOCK_FACTORIES:
                        key = cm.lock_key(t.attr)
                        cm.locks[t.attr] = LockDecl(
                            key=key, kind=_lock_kind(node.value, callee),
                            cls=cm.name, attr=t.attr,
                            site=Site(cm.path, node.lineno))
                        continue
                if tref is not None:
                    cm.attr_types.setdefault(t.attr, tref)


def _infer_value_type(model: LockModel, cm: ClassModel, value: ast.expr,
                      env: Dict[str, _TypeRef]) -> Optional[_TypeRef]:
    """Best-effort type of an assigned value (constructor calls, annotated
    params, list comprehensions of constructors, ``a or Default()``,
    ``self.x + self.y`` list concat)."""
    if isinstance(value, ast.Call):
        callee = _dotted(value.func)
        if callee:
            short = callee.split(".")[-1]
            if short in model.classes:
                return _TypeRef(short)
            fn = model.functions.get(short)
            if fn is not None and fn.returns and fn.returns in model.classes:
                return _TypeRef(fn.returns)
    if isinstance(value, ast.Name):
        return env.get(value.id)
    if isinstance(value, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
        if isinstance(value.elt, ast.Call):
            callee = _dotted(value.elt.func)
            if callee and callee.split(".")[-1] in model.classes:
                return _TypeRef(callee.split(".")[-1], container=True)
    if isinstance(value, ast.BoolOp):
        for v in value.values:
            got = _infer_value_type(model, cm, v, env)
            if got:
                return got
    if isinstance(value, ast.BinOp) and isinstance(value.op, ast.Add):
        left = _infer_value_type(model, cm, value.left, env)
        right = _infer_value_type(model, cm, value.right, env)
        if left and left.container and right and right.container \
                and left.cls == right.cls:
            return left
    if isinstance(value, ast.Attribute) and _is_self_attr(value):
        return cm.attr_types.get(value.attr)
    return None


def _collect_guarded_decls(cm: ClassModel, text: str):
    """``# dstpu: guarded-by[attr, lock]`` comments inside the class body
    declare the contract explicitly (for attrs whose locked writes live
    behind helper methods the inference can't see through)."""
    start = cm.node.lineno
    end = getattr(cm.node, "end_lineno", start) or start
    for i, line in enumerate(text.splitlines()[start - 1:end], start):
        m = _GUARDED_BY_RE.search(line)
        if m:
            cm.guarded.setdefault(m.group(1), m.group(2))


# ---------------------------------------------------------------------------
# pass 3: the held-lock walk
# ---------------------------------------------------------------------------
class _MethodWalker:
    """Walks one method/function body tracking the lexically-held lock set,
    recording acquisitions, resolved calls, blocking calls, and attribute
    accesses into a :class:`_MethodFacts`."""

    def __init__(self, model: LockModel, cm: Optional[ClassModel],
                 path: str, func: ast.AST):
        self.model = model
        self.cm = cm
        self.path = path
        self.func = func
        self.facts = _MethodFacts(
            cls=cm.name if cm else None,
            name=getattr(func, "name", "<lambda>"), path=path)
        self.env = self._param_env()

    # -- type environment -------------------------------------------------
    def _param_env(self) -> Dict[str, _TypeRef]:
        env: Dict[str, _TypeRef] = {}
        args = getattr(self.func, "args", None)
        if args is None:
            return env
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            got = _annotation_class(a.annotation)
            if got:
                env[a.arg] = _TypeRef(got[0], got[1])
        # flow-insensitive local bindings: two passes so simple chains
        # (x = self.cores; y = x[0]) resolve
        for _ in range(2):
            for node in ast.walk(self.func):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    got2 = self._expr_type(node.value, env)
                    if got2:
                        env.setdefault(node.targets[0].id, got2)
                elif isinstance(node, (ast.For, ast.AsyncFor)) \
                        and isinstance(node.target, ast.Name):
                    src = self._expr_type(node.iter, env)
                    if src and src.container:
                        env.setdefault(node.target.id, _TypeRef(src.cls))
                elif isinstance(node, ast.comprehension) \
                        and isinstance(node.target, ast.Name):
                    src = self._expr_type(node.iter, env)
                    if src and src.container:
                        env.setdefault(node.target.id, _TypeRef(src.cls))
        return env

    def _expr_type(self, expr: ast.expr,
                   env: Optional[Dict[str, _TypeRef]] = None) -> Optional[_TypeRef]:
        env = self.env if env is None else env
        if isinstance(expr, ast.Name):
            if expr.id == "self" and self.cm is not None:
                return _TypeRef(self.cm.name)
            if expr.id in env:
                return env[expr.id]
            stem = _module_stem(self.path)
            return self.model.module_globals.get(stem, {}).get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self._expr_type(expr.value, env)
            if base and not base.container:
                owner = self.model.classes.get(base.cls)
                if owner:
                    return owner.attr_types.get(expr.attr)
            return None
        if isinstance(expr, ast.Subscript):
            base = self._expr_type(expr.value, env)
            if base and base.container:
                return _TypeRef(base.cls)
            return None
        if isinstance(expr, ast.Call):
            func = expr.func
            if isinstance(func, ast.Attribute) and func.attr in (
                    "get", "pop", "popleft", "setdefault"):
                base = self._expr_type(func.value, env)
                if base and base.container:
                    return _TypeRef(base.cls)
            if isinstance(func, ast.Attribute) and func.attr == "values":
                base = self._expr_type(func.value, env)
                if base and base.container:
                    return base
            callee = _dotted(func)
            if callee:
                short = callee.split(".")[-1]
                if short in self.model.classes:
                    return _TypeRef(short)
                fn = self.model.functions.get(short)
                if fn is not None and fn.returns \
                        and fn.returns in self.model.classes:
                    return _TypeRef(fn.returns)
            return None
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                got = self._expr_type(v, env)
                if got:
                    return got
        return None

    # -- lock resolution ---------------------------------------------------
    def _lock_key_of(self, expr: ast.expr) -> Optional[str]:
        """``self._cond`` / ``vcore.step_lock`` / module ``_BUILD_LOCK`` ->
        the model lock key, or None when the expression is not a known
        lock."""
        if isinstance(expr, ast.Attribute):
            if _is_self_attr(expr) and self.cm is not None:
                if expr.attr in self.cm.locks:
                    return self.cm.lock_key(expr.attr)
                return None
            base = self._expr_type(expr.value)
            if base and not base.container:
                owner = self.model.classes.get(base.cls)
                if owner and expr.attr in owner.locks:
                    return owner.lock_key(expr.attr)
            return None
        if isinstance(expr, ast.Name):
            stem = _module_stem(self.path)
            key = f"{stem}.{expr.id}"
            if key in self.model.module_locks:
                return key
        return None

    # -- the walk -----------------------------------------------------------
    def walk(self):
        body = getattr(self.func, "body", [])
        if isinstance(body, list):
            self._walk_body(body, ())
        return self.facts

    def _walk_body(self, body: List[ast.stmt], held: Tuple[str, ...]):
        for stmt in body:
            self._walk_stmt(stmt, held)

    def _walk_stmt(self, stmt: ast.stmt, held: Tuple[str, ...]):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # nested defs run later, not under this lexical lock
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in stmt.items:
                expr = item.context_expr
                # `with self._lock:` and `with lock.acquire_timeout(..)`:
                key = self._lock_key_of(expr)
                if key is None and isinstance(expr, ast.Call):
                    self._scan_expr(expr, held)
                    continue
                if key is not None:
                    self.facts.acquisitions.append(_AcqFact(
                        lock=key, site=Site(self.path, stmt.lineno),
                        held=held + tuple(acquired)))
                    acquired.append(key)
                else:
                    self._scan_expr(expr, held)
            self._walk_body(stmt.body, held + tuple(acquired))
            return
        for expr in _stmt_exprs(stmt):
            self._scan_expr(expr, held)
        self._record_writes(stmt, held)
        for body in _sub_bodies(stmt):
            self._walk_body(body, held)

    def _record_writes(self, stmt: ast.stmt, held: Tuple[str, ...]):
        targets: List[Tuple[ast.expr, str]] = []
        if isinstance(stmt, ast.Assign):
            targets = [(t, "assign") for t in stmt.targets]
        elif isinstance(stmt, ast.AugAssign):
            targets = [(stmt.target, "augassign")]
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets = [(stmt.target, "assign")]
        for t, kind in targets:
            if isinstance(t, ast.Tuple):
                for elt in t.elts:
                    targets.append((elt, kind))
                continue
            if _is_self_attr(t):
                self.facts.writes.append(_AccessFact(
                    attr=t.attr, site=Site(self.path, stmt.lineno),
                    held=held, kind=kind))
            elif isinstance(t, ast.Subscript) and _is_self_attr(t.value):
                self.facts.writes.append(_AccessFact(
                    attr=t.value.attr, site=Site(self.path, stmt.lineno),
                    held=held, kind="subscript"))

    def _scan_expr(self, expr: ast.expr, held: Tuple[str, ...]):
        for node in ast.walk(expr):
            if isinstance(node, ast.Lambda):
                continue  # deferred execution
            if isinstance(node, ast.Call):
                self._scan_call(node, held)
            elif isinstance(node, ast.Attribute) and _is_self_attr(node) \
                    and isinstance(node.ctx, ast.Load):
                self.facts.reads.append(_AccessFact(
                    attr=node.attr, site=Site(self.path, node.lineno),
                    held=held, kind="read"))

    def _scan_call(self, call: ast.Call, held: Tuple[str, ...]):
        func = call.func
        dotted = _dotted(func)

        # explicit acquire()/release() on a known lock
        if isinstance(func, ast.Attribute) and func.attr == "acquire":
            key = self._lock_key_of(func.value)
            if key is not None:
                self.facts.acquisitions.append(_AcqFact(
                    lock=key, site=Site(self.path, call.lineno), held=held,
                    timeout=_call_timeout_bounded(call)))
                return

        # in-place mutator on a self attribute: self.q.append(x)
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS \
                and _is_self_attr(func.value):
            self.facts.writes.append(_AccessFact(
                attr=func.value.attr, site=Site(self.path, call.lineno),
                held=held, kind="mutator"))

        if held:
            self._scan_blocking(call, func, dotted, held)

        # resolved calls for the interprocedural graph
        if isinstance(func, ast.Attribute):
            recv = func.value
            if isinstance(recv, ast.Name) and recv.id == "self" \
                    and self.cm is not None:
                if func.attr in self.cm.methods:
                    self.facts.calls.append(_CallFact(
                        callee=(self.cm.name, func.attr),
                        site=Site(self.path, call.lineno), held=held,
                        is_self_call=True, recv="self"))
                return
            base = self._expr_type(recv)
            if base and not base.container and base.cls in self.model.classes:
                owner = self.model.classes[base.cls]
                if func.attr in owner.methods:
                    self.facts.calls.append(_CallFact(
                        callee=(base.cls, func.attr),
                        site=Site(self.path, call.lineno), held=held,
                        is_self_call=False,
                        recv=_dotted(recv) or base.cls.lower()))
            return
        if isinstance(func, ast.Name) and func.id in self.model.functions:
            self.facts.calls.append(_CallFact(
                callee=(None, func.id), site=Site(self.path, call.lineno),
                held=held, is_self_call=False, recv=""))

    def _scan_blocking(self, call: ast.Call, func: ast.expr,
                       dotted: Optional[str], held: Tuple[str, ...]):
        if dotted in _BLOCKING_CALLS:
            self.facts.blocking.append(_BlockFact(
                site=Site(self.path, call.lineno), held=held,
                desc=f"{dotted}()", reason=_BLOCKING_CALLS[dotted]))
            return
        if not isinstance(func, ast.Attribute):
            return
        name = func.attr
        if name not in _BLOCKING_METHODS:
            return
        if name == "block_until_ready":
            self.facts.blocking.append(_BlockFact(
                site=Site(self.path, call.lineno), held=held,
                desc=".block_until_ready()",
                reason=_BLOCKING_METHODS[name]))
            return
        # wait/wait_for on a lock we hold RELEASES it — that is the
        # condition-variable protocol, not a blocking hazard
        key = self._lock_key_of(func.value)
        if name in ("wait", "wait_for") and key is not None and key in held:
            return
        if _call_timeout_bounded(call):
            return
        recv_name = None
        if isinstance(func.value, ast.Attribute):
            recv_name = func.value.attr
        elif isinstance(func.value, ast.Name):
            recv_name = func.value.id
        if name in ("get", "join", "wait", "wait_for"):
            # only synchronization-looking receivers; dict.get(k) is fine
            if recv_name is None or not _BLOCKING_RECV_HINTS.search(recv_name):
                return
        rendered = _dotted(func) or f"<expr>.{name}"
        self.facts.blocking.append(_BlockFact(
            site=Site(self.path, call.lineno), held=held,
            desc=f"{rendered}()", reason=_BLOCKING_METHODS[name]))


def _collect_method_facts(model: LockModel, path: str, tree: ast.AST):
    if not isinstance(tree, ast.Module):
        return
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            facts = _MethodWalker(model, None, path, node).walk()
            model.method_facts.setdefault((None, node.name), facts)
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        cm = model.classes.get(node.name)
        if cm is None or cm.node is not node:
            continue
        for meth in cm.methods.values():
            facts = _MethodWalker(model, cm, path, meth).walk()
            model.method_facts[(cm.name, facts.name)] = facts


def _infer_guarded(model: LockModel):
    """Attributes written under an own-class lock in any non-``__init__``
    method become guarded class-wide (the lock attrs themselves are
    excluded)."""
    for cm in model.classes.values():
        if not cm.locks:
            cm.guarded.clear()  # guarded-by decls need a lock to mean anything
            continue
        own_keys = {cm.lock_key(a): a for a in cm.locks}
        for (cls, mname), facts in model.method_facts.items():
            if cls != cm.name or mname == "__init__":
                continue
            for w in facts.writes:
                if w.attr in cm.locks or w.attr in cm.guarded:
                    continue
                for key in w.held:
                    if key in own_keys:
                        cm.guarded[w.attr] = own_keys[key]
                        break
        # declared guards must reference a real lock attr
        for attr in list(cm.guarded):
            if cm.guarded[attr] not in cm.locks:
                del cm.guarded[attr]


def _derive_edges(model: LockModel):
    for (cls, mname), facts in model.method_facts.items():
        for acq in facts.acquisitions:
            if acq.lock in acq.held:
                decl = model.lock_decl(acq.lock)
                entry = (acq.lock, acq.site, "direct re-acquisition")
                model.reentrant_acquires.append(entry)
                if decl is not None and not decl.reentrant:
                    model.reentrant_hazards.append(entry)
                continue
            for h in acq.held:
                model.add_edge(h, acq.lock, acq.site)
        for call in facts.calls:
            if not call.held:
                continue
            inner = model.may_acquire(call.callee)
            for lock in inner:
                if lock in call.held:
                    decl = model.lock_decl(lock)
                    via = (f"call to "
                           f"{call.callee[0] or call.callee[1]}"
                           f"{'.' + call.callee[1] if call.callee[0] else ''}"
                           f"() which acquires it")
                    entry = (lock, call.site, via)
                    model.reentrant_acquires.append(entry)
                    if decl is not None and not decl.reentrant:
                        model.reentrant_hazards.append(entry)
                    continue
                for h in call.held:
                    model.add_edge(h, lock, call.site)
