"""Optimized linear / LoRA (reference deepspeed/linear/)."""

from deepspeed_tpu.linear.optimized_linear import (
    LoRAConfig,
    QuantizationConfig,
    init_optimized_linear,
    lora_trainable_mask,
    merge_lora,
    optimized_linear,
    optimized_linear_partition_specs,
)

__all__ = [
    "LoRAConfig",
    "QuantizationConfig",
    "init_optimized_linear",
    "lora_trainable_mask",
    "merge_lora",
    "optimized_linear",
    "optimized_linear_partition_specs",
]
