"""OptimizedLinear: sharded frozen base + LoRA adapters (+ quantized base).

Analogue of the reference ``linear/optimized_linear.py`` (``OptimizedLinear``
dispatching to ``LoRAOptimizedLinear``) + ``linear/quantization.py``
(``QuantizedParameter``): the full-rank base weight is frozen (optionally
stored int8 with block scales), sharded over the model axis, and only the
low-rank A/B adapters train.

Functional form:
  params = init_optimized_linear(key, in_f, out_f, lora, quant)
  y      = optimized_linear(params, x, lora, quant)
  specs  = optimized_linear_partition_specs(lora)      # for initialize()
  mask   = lora_trainable_mask(params)                 # freeze the base
"""

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.quantizer.block_quant import (
    QuantizedTensor,
    dequantize_blockwise,
    quantize_blockwise,
)
from deepspeed_tpu.parallel.topology import MODEL_AXIS


@dataclass
class LoRAConfig:
    """Reference linear/config.py LoRAConfig."""

    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1  # shard the frozen base over `model`


@dataclass
class QuantizationConfig:
    """Reference linear/config.py QuantizationConfig."""

    q_bits: int = 8
    group_size: int = 512
    quantized_weights: bool = True


def init_optimized_linear(
    key: jax.Array,
    in_features: int,
    out_features: int,
    lora: LoRAConfig = LoRAConfig(),
    quant: Optional[QuantizationConfig] = None,
    dtype=jnp.float32,
    base_weight: Optional[jax.Array] = None,
) -> Dict[str, Any]:
    """Build the param dict: frozen base [in, out] (int8 payload + scales
    when ``quant``), trainable lora_a [in, r] (kaiming-ish) and lora_b
    [r, out] (zeros — adapters start as identity)."""
    k1, k2 = jax.random.split(key)
    if base_weight is None:
        base_weight = jax.random.normal(k1, (in_features, out_features), jnp.float32) * (
            in_features**-0.5
        )
    base_weight = base_weight.astype(dtype)
    if quant is not None and quant.quantized_weights:
        qt = quantize_blockwise(base_weight, bits=quant.q_bits, block_size=quant.group_size)
        base = {"values": qt.values, "scales": qt.scales}
    else:
        base = {"weight": base_weight}
    return {
        "base": base,
        "lora_a": (jax.random.normal(k2, (in_features, lora.lora_r)) * (in_features**-0.5)).astype(dtype),
        "lora_b": jnp.zeros((lora.lora_r, out_features), dtype),
    }


def _base_weight(params, quant: Optional[QuantizationConfig], shape, dtype):
    base = params["base"]
    if "weight" in base:
        return base["weight"]
    qt = QuantizedTensor(
        values=base["values"], scales=base["scales"], shape=shape,
        bits=quant.q_bits if quant else 8,
        block_size=quant.group_size if quant else 512,
    )
    return dequantize_blockwise(qt, dtype)


def optimized_linear(
    params: Dict[str, Any],
    x: jax.Array,
    lora: LoRAConfig = LoRAConfig(),
    quant: Optional[QuantizationConfig] = None,
) -> jax.Array:
    """y = x @ W_base + (alpha / r) * (x @ A) @ B  (base under
    stop_gradient — frozen like the reference's requires_grad=False)."""
    in_f = params["lora_a"].shape[0]
    out_f = params["lora_b"].shape[1]
    w = _base_weight(params, quant, (in_f, out_f), x.dtype)
    w = jax.lax.stop_gradient(w)
    y = x @ w.astype(x.dtype)
    scaling = lora.lora_alpha / lora.lora_r
    return y + scaling * (x @ params["lora_a"]) @ params["lora_b"]


def merge_lora(
    params: Dict[str, Any],
    lora: LoRAConfig = LoRAConfig(),
    quant: Optional[QuantizationConfig] = None,
) -> jax.Array:
    """Fold adapters into a dense weight (the hybrid-engine fuse / export
    path): W = W_base + (alpha/r) A@B."""
    in_f = params["lora_a"].shape[0]
    out_f = params["lora_b"].shape[1]
    w = _base_weight(params, quant, (in_f, out_f), params["lora_a"].dtype)
    return w + (lora.lora_alpha / lora.lora_r) * (params["lora_a"] @ params["lora_b"])


def optimized_linear_partition_specs(
    lora: LoRAConfig = LoRAConfig(), quant: Optional[QuantizationConfig] = None
) -> Dict[str, Any]:
    """PartitionSpecs: base sharded over `model` when base_weight_sharding>1
    (the reference's sharded frozen base); adapters replicated (tiny)."""
    shard = lora.base_weight_sharding > 1
    if quant is not None and quant.quantized_weights:
        base = {"values": P(MODEL_AXIS, None) if shard else P(), "scales": P()}
    else:
        base = {"weight": P(None, MODEL_AXIS) if shard else P(None, None)}
    return {"base": base, "lora_a": P(None, None), "lora_b": P(None, None)}


def lora_trainable_mask(params: Dict[str, Any]) -> Dict[str, Any]:
    """True for trainable leaves (adapters), False for the frozen base —
    feed to optax.masked / multi_transform to skip base updates."""
    return jax.tree.map(lambda _: False, {"base": params["base"]}) | {
        "lora_a": True,
        "lora_b": True,
    }
