"""TPU accelerator (JAX backend).

Concrete accelerator for TPU devices, the analogue of the reference's
``accelerator/cuda_accelerator.py``. Device enumeration, memory stats, and
dtype support come from the JAX runtime; the communication backend name is
``"xla"`` (collectives over ICI/DCN compiled by XLA, replacing NCCL).
"""

import os

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):
    def __init__(self, platform="tpu"):
        super().__init__()
        self._name = "tpu"
        self._platform = platform
        self._communication_backend_name = "xla"

    def _devices(self):
        import jax

        return jax.local_devices()

    def device_name(self, device_index=None):
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def device(self, device_index=None):
        devs = self._devices()
        return devs[device_index or 0]

    def device_count(self):
        return len(self._devices())

    def global_device_count(self):
        import jax

        return jax.device_count()

    def current_device(self):
        return 0

    def is_available(self):
        try:
            return self.device_count() > 0
        except Exception:
            return False

    def synchronize(self, device_index=None):
        import jax
        import jax.numpy as jnp

        jax.block_until_ready(jnp.zeros(()))

    def memory_stats(self, device_index=None):
        try:
            return self.device(device_index).memory_stats() or {}
        except Exception:
            return {}

    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        # fp16 compute is supported via XLA, though bf16 is native/preferred on TPU.
        return True

    def communication_backend_name(self):
        return self._communication_backend_name

    def create_op_builder(self, op_name):
        builder = self.get_op_builder(op_name)
        return builder() if builder else None

    def get_op_builder(self, op_name):
        from deepspeed_tpu.ops.op_builder import ALL_OPS

        return ALL_OPS.get(op_name)


class CPU_Accelerator(TPU_Accelerator):
    """CPU backend for cluster-free testing (virtual multi-device mesh via
    ``--xla_force_host_platform_device_count``); reference analogue:
    ``accelerator/cpu_accelerator.py`` + the gloo path in tests."""

    def __init__(self):
        super().__init__(platform="cpu")
        self._name = "cpu"
        self._communication_backend_name = "xla"

    def _devices(self):
        import jax

        return jax.devices("cpu")

    def device_name(self, device_index=None):
        if device_index is None:
            return "cpu"
        return f"cpu:{device_index}"

    def is_bf16_supported(self):
        return True
