"""Accelerator singleton detection.

Analogue of the reference ``accelerator/real_accelerator.py``
(``get_accelerator`` :51, ``DS_ACCELERATOR`` env override :59,
``set_accelerator`` :264). Detection order: explicit override env
``DS_ACCELERATOR`` ∈ {tpu, cpu} → JAX default backend.
"""

import os

from deepspeed_tpu.utils.logging import logger

_accelerator = None


def _detect():
    from deepspeed_tpu.accelerator.tpu_accelerator import CPU_Accelerator, TPU_Accelerator

    override = os.environ.get("DS_ACCELERATOR")
    if override is not None:
        if override == "cpu":
            return CPU_Accelerator()
        if override in ("tpu", "axon"):
            return TPU_Accelerator()
        raise ValueError(f"DS_ACCELERATOR={override} not supported (tpu|cpu)")
    try:
        import jax

        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    if backend == "cpu":
        return CPU_Accelerator()
    return TPU_Accelerator(platform=backend)


def get_accelerator():
    global _accelerator
    if _accelerator is None:
        _accelerator = _detect()
        logger.info(f"Setting ds_accelerator to {_accelerator._name}")
    return _accelerator


def set_accelerator(accel):
    global _accelerator
    _accelerator = accel


def is_current_accelerator_supported():
    return True
