"""Accelerator abstraction.

TPU-native analogue of the reference ``accelerator/abstract_accelerator.py``
(``DeepSpeedAccelerator`` ABC :10-306, ~60 abstract methods). The JAX execution
model removes the need for explicit stream/event management (XLA orders all
dispatched work per device), so the stream/event surface collapses to no-ops
retained for API compatibility; memory stats map to ``Device.memory_stats()``.
"""

import abc
from abc import ABC


class DeepSpeedAccelerator(ABC):
    def __init__(self):
        self._name = None
        self._communication_backend_name = None

    # ---- device APIs ----
    @abc.abstractmethod
    def device_name(self, device_index=None):
        ...

    @abc.abstractmethod
    def device(self, device_index=None):
        ...

    @abc.abstractmethod
    def device_count(self):
        ...

    @abc.abstractmethod
    def current_device(self):
        ...

    def set_device(self, device_index):
        # JAX places computations by sharding, not a thread-local device.
        pass

    def current_device_name(self):
        return self.device_name(self.current_device())

    @abc.abstractmethod
    def is_available(self):
        ...

    # ---- RNG APIs (functional in JAX: key-splitting, see runtime/rng) ----
    def manual_seed(self, seed):
        pass

    def initial_seed(self):
        return 0

    # ---- synchronization ----
    @abc.abstractmethod
    def synchronize(self, device_index=None):
        ...

    # streams/events are no-ops: XLA async dispatch is program-ordered
    def stream(self, stream):
        import contextlib

        return contextlib.nullcontext()

    def current_stream(self, device_index=None):
        return None

    def default_stream(self, device_index=None):
        return None

    class Event:
        def __init__(self, enable_timing=False):
            import time

            self._t = time.time

        def record(self, stream=None):
            self.t0 = self._t()

        def synchronize(self):
            pass

        def elapsed_time(self, other):
            return (other.t0 - self.t0) * 1000.0

    # ---- memory APIs ----
    @abc.abstractmethod
    def memory_stats(self, device_index=None):
        ...

    def memory_allocated(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index=None):
        return self.memory_stats(device_index).get("peak_bytes_in_use", 0)

    def memory_reserved(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_reserved", self.memory_allocated(device_index))

    def max_memory_reserved(self, device_index=None):
        return self.max_memory_allocated(device_index)

    def total_memory(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index=None):
        return self.total_memory(device_index) - self.memory_allocated(device_index)

    def empty_cache(self):
        pass

    def reset_peak_memory_stats(self, device_index=None):
        pass

    # ---- dtype support ----
    @abc.abstractmethod
    def is_bf16_supported(self):
        ...

    @abc.abstractmethod
    def is_fp16_supported(self):
        ...

    def supported_dtypes(self):
        import jax.numpy as jnp

        dtypes = [jnp.float32]
        if self.is_fp16_supported():
            dtypes.append(jnp.float16)
        if self.is_bf16_supported():
            dtypes.append(jnp.bfloat16)
        return dtypes

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16 if self.is_bf16_supported() else jnp.float32

    # ---- comm backend ----
    @abc.abstractmethod
    def communication_backend_name(self):
        ...

    # ---- graphs: jit is the TPU analogue of CUDA graphs ----
    def is_triton_supported(self):
        return False

    def create_graph(self):
        return None

    def capture_to_graph(self, graph, pool=None, stream=None):
        import contextlib

        return contextlib.nullcontext()

    def replay_graph(self, graph):
        pass

    # ---- op builder (Pallas kernel registry; reference :276-282) ----
    @abc.abstractmethod
    def create_op_builder(self, op_name):
        ...

    @abc.abstractmethod
    def get_op_builder(self, op_name):
        ...

    def on_accelerator(self, tensor):
        return True
